"""PTQ observers (reference: python/paddle/quantization/observers/abs_max.py
and PaddleSlim's observer zoo — collect activation statistics in eval mode to
derive quantization scales)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..nn.layer.layers import Layer


class _BaseObserver(Layer):
    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        self._observe(np.asarray(jax.device_get(x._data), np.float32))
        return x

    def _observe(self, arr):
        raise NotImplementedError

    def cal_thresholds(self):
        pass

    def scales(self):
        self.cal_thresholds()
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def quant_axis(self):
        return None

    def bit_length(self):
        return self._quant_bits


class AbsmaxObserver(_BaseObserver):
    """Running max of |x| (reference: observers/abs_max.py AbsmaxObserver)."""

    def _observe(self, arr):
        m = float(np.max(np.abs(arr))) if arr.size else 0.0
        self._scale = m if self._scale is None else max(self._scale, m)


class EMAObserver(_BaseObserver):
    """Exponential moving average of per-batch abs-max."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self._moving_rate = moving_rate

    def _observe(self, arr):
        m = float(np.max(np.abs(arr))) if arr.size else 0.0
        r = self._moving_rate
        self._scale = m if self._scale is None else r * self._scale + (1 - r) * m


class AVGObserver(_BaseObserver):
    """Average of per-batch abs-max (reference: observers/avg.py)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._sum, self._n = 0.0, 0

    def _observe(self, arr):
        self._sum += float(np.max(np.abs(arr))) if arr.size else 0.0
        self._n += 1
        self._scale = self._sum / max(self._n, 1)


class PercentObserver(_BaseObserver):
    """Percentile of |x| (clips outliers; reference: PaddleSlim
    PercentileObserver)."""

    def __init__(self, quant_bits=8, percent=0.999, sample_limit=1 << 20):
        super().__init__(quant_bits)
        self._percent = percent
        self._samples = []
        self._limit = sample_limit

    def _observe(self, arr):
        flat = np.abs(arr).ravel()
        if flat.size > self._limit:
            flat = np.random.default_rng(0).choice(flat, self._limit, replace=False)
        self._samples.append(flat)

    def cal_thresholds(self):
        if self._samples:
            allv = np.concatenate(self._samples)
            self._scale = float(np.quantile(allv, self._percent))


class HistObserver(_BaseObserver):
    """Histogram-based threshold (simplified KL-free variant: pick the bin
    edge covering `coverage` of mass; reference: observers/hist.py)."""

    def __init__(self, quant_bits=8, bins_count=2048, coverage=0.9999):
        super().__init__(quant_bits)
        self._bins = bins_count
        self._coverage = coverage
        self._hist = None
        self._max = 0.0

    def _observe(self, arr):
        m = float(np.max(np.abs(arr))) if arr.size else 0.0
        self._max = max(self._max, m)
        hist, _ = np.histogram(np.abs(arr), bins=self._bins, range=(0, self._max or 1.0))
        if self._hist is None or self._hist.shape != hist.shape:
            self._hist = hist.astype(np.float64)
        else:
            self._hist += hist

    def cal_thresholds(self):
        if self._hist is None:
            return
        cum = np.cumsum(self._hist)
        total = cum[-1] or 1.0
        idx = int(np.searchsorted(cum / total, self._coverage))
        self._scale = (idx + 1) / self._bins * (self._max or 1.0)
