"""Quantization-aware training entry (reference:
python/paddle/quantization/qat.py — ``QAT(config).quantize(model)`` swaps
quantizable layers for fake-quant wrappers; training then proceeds normally
and the straight-through estimator carries gradients)."""
from .quantize import _convert_inplace


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        n = _convert_inplace(model, self._config)
        if n == 0:
            raise ValueError("no quantizable layer matched the QuantConfig")
        return model

    def convert(self, model, inplace=False):
        """QAT model → inference form. Fake-quant layers already simulate
        int8 numerics; conversion is the identity here (export handles real
        int8 packing when targeted)."""
        return model if inplace else __import__("copy").deepcopy(model)
