"""Post-training quantization (reference: python/paddle/quantization/ptq.py —
``PTQ(config).quantize(model)`` inserts observers; run calibration batches in
eval mode; ``convert`` freezes observed scales into fake-quant layers)."""
from .quanters import FakeQuanterWithAbsMaxObserver
from .quantize import _convert_inplace
from ..framework.core import Tensor


class PTQ:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        n = _convert_inplace(model, self._config)
        if n == 0:
            raise ValueError("no quantizable layer matched the QuantConfig")
        model.eval()
        return model

    def convert(self, model, inplace=False):
        """Freeze observer statistics into static scales: every observer
        becomes a fixed fake-quanter whose scale no longer updates."""
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        from .observers import _BaseObserver

        def freeze(layer):
            for name, child in list(layer._sub_layers.items()):
                if isinstance(child, _BaseObserver):
                    scale = child.scales()
                    fq = FakeQuanterWithAbsMaxObserver(bit_length=child.bit_length())
                    fq.scale._data = scale._data
                    fq.eval()
                    layer._sub_layers[name] = fq
                    setattr(layer, name, fq)
                else:
                    freeze(child)

        freeze(model)
        model.eval()
        return model
