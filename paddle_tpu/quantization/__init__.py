"""paddle.quantization parity (reference: python/paddle/quantization/ —
QuantConfig in config.py, QAT in qat.py, PTQ in ptq.py, observers/ and
quanters/ subpackages).

TPU-native design: fake-quantization is expressed as traceable jnp ops with a
straight-through estimator (x + stop_gradient(q(x) − x)), so QAT runs inside
the same jit-compiled train step as everything else — no custom kernels, and
XLA fuses the quant/dequant pair into neighbouring ops.
"""
from .config import QuantConfig
from .observers import (
    AbsmaxObserver,
    AVGObserver,
    EMAObserver,
    HistObserver,
    PercentObserver,
)
from .quanters import (
    FakeQuanterChannelWiseAbsMaxObserver,
    FakeQuanterWithAbsMaxObserver,
    fake_quant,
)
from .qat import QAT
from .ptq import PTQ
from .quantize import quanted_layers

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "AbsmaxObserver", "AVGObserver", "EMAObserver", "HistObserver", "PercentObserver",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "fake_quant", "quanted_layers",
]
