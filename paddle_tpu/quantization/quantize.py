"""Quanted layer wrappers (reference: paddle/nn/quant/qat/linear.py
QuantedLinear, conv.py QuantedConv2D — forward = act_quanter(x) ·
weight_quanter(W))."""
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..nn.layer.layers import Layer


class QuantedLinear(Layer):
    def __init__(self, layer: Linear, q_config):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter = q_config.activation() if q_config.activation else None
        self.weight_quanter = q_config.weight() if q_config.weight else None

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer: Conv2D, q_config):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter = q_config.activation() if q_config.activation else None
        self.weight_quanter = q_config.weight() if q_config.weight else None

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        saved = self._inner.weight
        try:
            self._inner.weight = w
            return self._inner.forward(x)
        finally:
            self._inner.weight = saved


QAT_LAYER_MAP = {
    Linear: QuantedLinear,
    Conv2D: QuantedConv2D,
}


def quanted_layers():
    return dict(QAT_LAYER_MAP)


def _convert_inplace(model, config):
    """Replace quantizable sublayers per config; returns count converted."""
    n = 0
    for name, child in list(model._sub_layers.items()):
        cfg = config._get_config_for_layer(child, name)
        target = QAT_LAYER_MAP.get(type(child))
        if cfg is not None and target is not None and (cfg.activation or cfg.weight):
            model._sub_layers[name] = target(child, cfg)
            setattr(model, name, model._sub_layers[name])
            n += 1
        else:
            n += _convert_inplace(child, config)
    return n
