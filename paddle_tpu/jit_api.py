"""Compiled execution (reference analogue: @paddle.jit.to_static +
dygraph-to-static, python/paddle/jit/ — but TPU-native: tracing IS jax).

Key design (SURVEY.md §3.1): the dygraph tape is built from traceable jax
ops, so wrapping a whole train step in jax.jit compiles forward + backward +
optimizer into ONE XLA program. `TrainStep` is that wrapper; `jit`/`to_static`
are the user-facing decorators.
"""
import functools
import time as _time

import jax
import jax.numpy as jnp

from .framework import random as prandom
from .framework.core import Tensor, _bump_mutation_version, to_tensor
from .observability import compilemem as _compilemem
from .observability import devprof as _devprof
from .observability import dynamics as _dynamics
from .observability import flightrec as _flightrec
from .observability import goodput as _goodput
from .observability import tracing as _tracing
from .observability import watchdog as _watchdog
from .observability.metrics import registry as _registry
from .testing import chaos
from .utils.envs import env_int as _env_int

#: consecutive non-finite (NaN/Inf loss or grads) steps tolerated before
#: the sentinel raises NonFiniteLossError; <= 0 disables the guard
NONFINITE_TOLERANCE_ENV = "PADDLE_NONFINITE_TOLERANCE"
#: host-side check cadence in dispatches (reading the device counters
#: synchronizes on the step); default max(tolerance, 16)
NONFINITE_CHECK_ENV = "PADDLE_NONFINITE_CHECK_EVERY"


class NonFiniteLossError(FloatingPointError):
    """The non-finite sentinel tripped: loss or gradients were NaN/Inf for
    PADDLE_NONFINITE_TOLERANCE consecutive steps. Every one of those
    updates was SKIPPED in-program (weights are uncorrupted) — but a model
    that cannot produce a finite step anymore is not training, so the loop
    is stopped instead of burning the rest of the job silently."""


def jit(fn=None, static_argnums=None, donate_argnums=None, backend=None):
    """Compile a Tensor->Tensor function with XLA. An implicit PRNG key is
    threaded per call so dropout stays random without retracing."""

    def deco(f):
        kw = {}
        # user indexes refer to f's positional args; inner prepends the key,
        # so shift by exactly 1 (inner takes *args positionally, not packed)
        if static_argnums is not None:
            nums = static_argnums if isinstance(static_argnums, (list, tuple)) else (static_argnums,)
            kw["static_argnums"] = tuple(a + 1 for a in nums)
        if donate_argnums is not None:
            nums = donate_argnums if isinstance(donate_argnums, (list, tuple)) else (donate_argnums,)
            kw["donate_argnums"] = tuple(a + 1 for a in nums)

        def _inner(key, *args, **kwargs):
            with prandom.rng_guard(key):
                return f(*args, **kwargs)

        # the compile-ledger wrapper (ISSUE 8): records every (re)trace
        # of this program — key'd per decorated function, so shape drift
        # on ONE function reads as churn, not as distinct programs
        inner = _compilemem.ledgered_jit(
            _inner, key=f"jit.{getattr(f, '__name__', 'fn')}", **kw)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return inner(prandom.next_key(), *args, **kwargs)

        wrapper._jax_fn = inner
        return wrapper

    return deco(fn) if fn is not None else deco


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """@paddle.jit.to_static parity. If applied to a Layer, returns a wrapper
    whose __call__ runs the compiled functional forward. Honors
    jit.enable_to_static(False) (reference: ProgramTranslator.enable) — the
    object is returned unconverted for eager debugging — and skips functions
    marked @not_to_static."""
    from .nn.layer.layers import Layer

    def deco(obj):
        import importlib

        jit_ns = importlib.import_module(__package__ + ".jit")
        if not getattr(jit_ns, "_to_static_enabled", True):
            return obj
        if getattr(obj, "_not_to_static", False) or jit_ns.is_ignored(obj):
            return obj
        if isinstance(obj, Layer):
            return StaticLayer(obj)
        return jit(_convert_control_flow(obj))

    return deco(function) if function is not None else deco


def _convert_control_flow(fn):
    """Attempt the dy2static AST rewrite (data-dependent if/while/for →
    lax.cond/while_loop/fori_loop); fall back to the plain trace when the
    source is unavailable or unconvertible (reference: convert_to_static
    falling back to dygraph, python/paddle/jit/dy2static/convert_call_func.py)."""
    from .jit.dy2static import convert_control_flow

    try:
        return convert_control_flow(fn)
    except Exception:
        return fn


class StaticLayer:
    """A Layer compiled to a pure XLA callable: params/buffers become jit
    arguments via functional_call (reference: PartialProgramLayer running the
    traced program via the run_program op, python/paddle/jit/dy2static).

    The layer's forward gets the dy2static control-flow rewrite (tensor
    if/while -> lax.cond/while_loop) when convertible — same contract as
    function to_static."""

    def __init__(self, layer):
        self._layer = layer
        fwd_fn = _convert_control_flow(type(layer).forward)
        if getattr(fwd_fn, "__dy2static__", False):
            import types

            layer.forward = types.MethodType(fwd_fn, layer)

        def fwd(state, key, args, kwargs):
            with prandom.rng_guard(key):
                out = layer.functional_call(
                    {k: Tensor(v, stop_gradient=True) for k, v in state.items()}, *args, **kwargs
                )
            return out

        # per-INSTANCE key (same convention as static.exec): N compiled
        # instances of one class are N intended programs, not churn
        self._fwd = _compilemem.ledgered_jit(
            fwd, key=f"static_layer.{type(layer).__name__}"
                     f"[{id(layer) & 0xffff:x}]")

    def __call__(self, *args, **kwargs):
        state = self._layer.raw_state_dict()
        return self._fwd(state, prandom.next_key(), args, kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """One fully-compiled training step over a dygraph model.

    forward (+AMP autocast) → tape backward → grad clip → optimizer update →
    buffer (BN stats) update, all inside ONE jax.jit with donated state.
    Mirrors what the reference needed eager codegen + fused kernels +
    interpreter scheduling for (SURVEY.md §3.1 consequence).

    loss_fn(outputs, *labels) -> scalar Tensor.

    accumulate_steps=k (reference: fleet gradient_merge_optimizer.py /
    passes/auto_parallel_gradient_merge.py) runs k micro-batches through a
    lax.scan INSIDE the one compiled step: forward+backward per micro-batch,
    f32 grad accumulation, ONE optimizer update on the averaged grads. The
    batch's leading dim must be divisible by k. Composes with AMP (loss
    scale seeds each micro-backward; the finite check runs once on the
    merged grads), grad clip (applied to merged grads) and the
    DistributedTrainStep shardings (micro-split happens after sharding).
    """

    def __init__(self, model, loss_fn, optimizer, n_labels=1, scaler=None, mesh_shardings=None,
                 metrics_bus=None, accumulate_steps=1, nonfinite_guard=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n_labels = n_labels
        self.scaler = scaler
        self.metrics_bus = metrics_bus
        self.accumulate_steps = int(accumulate_steps)
        if self.accumulate_steps < 1:
            raise ValueError(f"accumulate_steps must be >= 1, got {accumulate_steps}")

        self._trainable = {
            k: p for k, p in dict(model.named_parameters()).items() if not p.stop_gradient
        }
        self._frozen = {
            k: p for k, p in dict(model.named_parameters()).items() if p.stop_gradient
        }
        self._buffers = dict(model.named_buffers())
        self.opt_state = optimizer.init_state(self._trainable)
        self._scaler_state = scaler.init_state() if scaler is not None else None
        # non-finite sentinel (ISSUE 9 satellite): an in-program guard
        # skips the optimizer update when loss/grads go NaN/Inf — weights
        # never absorb a poisoned step — and device-resident counters
        # (consecutive + total skips) let the host raise after K
        # consecutive skips instead of training garbage forever. Default
        # ON without a scaler; with a DYNAMIC loss scaler the default is
        # OFF — the scaler's warm-down legitimately produces runs of
        # overflowed (skipped) steps while the scale adjusts, and killing
        # those jobs would defeat the scaler (pass nonfinite_guard=True to
        # arm it anyway, accepting that semantics).
        # PADDLE_NONFINITE_TOLERANCE<=0 or nonfinite_guard=False disables
        # it entirely (nf_state is None and the compiled program carries
        # no counters).
        self._nf_tolerance = _env_int(NONFINITE_TOLERANCE_ENV, 3)
        nf_on = (nonfinite_guard if nonfinite_guard is not None
                 else scaler is None) and self._nf_tolerance > 0
        self._nf_state = {"consec": jnp.zeros((), jnp.int32),
                          "total": jnp.zeros((), jnp.int32)} if nf_on else None
        # reading the device counters synchronizes on the dispatch, so the
        # host check is cadence-gated well above the tolerance; the consec
        # counter is monotone WHILE stuck, so a model that stopped
        # producing finite steps is still always caught at the next read
        self._nf_check_every = max(1, _env_int(NONFINITE_CHECK_ENV,
                                               max(self._nf_tolerance, 16)))
        self._nf_reported = 0     # skips already counted to the registry
        self._nf_since_check = 0  # dispatches since the last host read
        # training-dynamics telemetry (ISSUE 13): a second donated carry —
        # per-layer-group grad/param/update norms, loss EWMA + spike
        # z-score, and the non-finite PROVENANCE mask (which group went
        # NaN/Inf first) — updated in-program every step and spilled to
        # the host once per PADDLE_DYNAMICS_EVERY_STEPS window. Disabled
        # (the default), _dynamics is None: the compiled program carries
        # nothing and the epilogue pays one is-None check.
        self._dynamics = _dynamics.DynamicsMonitor.from_env(self._trainable)
        self._dyn_state = (self._dynamics.init_state()
                           if self._dynamics is not None else None)
        self._dyn_since_check = 0
        # first dispatch pays XLA compile: goodput attributes it to "init"
        self._dispatched = False
        # register with the hang watchdog BEFORE the first step: a rank that
        # wedges in its first compile/collective must still be diagnosable
        # (the init beat gets the watchdog's longer startup deadline)
        _watchdog.arm_from_env()
        # device-time profiling plane (ISSUE 17): PADDLE_DEVPROF=1 samples
        # one timed dispatch per PADDLE_DEVPROF_SAMPLE_EVERY steps;
        # disabled, the step epilogue pays one is-None check
        _devprof.arm_from_env()

        opt = optimizer
        n_lab = n_labels
        acc = self.accumulate_steps
        dyn = self._dynamics

        def fwd_bwd(params, buffers, frozen, key, batch, scale):
            """One forward+tape-backward; returns (loss, grads, new_buffers).
            Grads stay loss-scale-scaled (unscaling happens once, merged)."""
            inputs = batch[:-n_lab] if n_lab else batch
            labels = batch[-n_lab:] if n_lab else ()
            overrides = {k: Tensor(v, stop_gradient=False) for k, v in params.items()}
            buf_over = {k: Tensor(v, stop_gradient=True) for k, v in buffers.items()}
            frozen_over = {k: Tensor(v, stop_gradient=True) for k, v in frozen.items()}
            # named_scope (not host spans): fwd/bwd/opt are fused into ONE
            # XLA program, so phase attribution lives in the HLO metadata and
            # shows up in xprof device traces, where host clocks cannot reach
            with prandom.rng_guard(key), jax.named_scope("forward"):
                out = model.functional_call(
                    {**overrides, **buf_over, **frozen_over},
                    *[Tensor(b) for b in inputs],
                    training=True,
                )
                outs = out if isinstance(out, (tuple, list)) else (out,)
                loss = loss_fn(*outs, *[Tensor(b, stop_gradient=True) for b in labels])
            with jax.named_scope("backward"):
                if scale is not None:
                    # seed the cotangent with the loss scale (≡ scaling the loss)
                    loss.backward(Tensor(jnp.ones_like(loss._data) * scale))
                else:
                    loss.backward()
                grads = {k: t.grad._data for k, t in overrides.items() if t.grad is not None}
            new_buffers = {k: t._data for k, t in buf_over.items()}
            return loss._data, grads, new_buffers

        def step_fn(params, buffers, frozen, opt_state, scaler_state,
                    nf_state, dyn_state, lr, key, batch):
            scale = scaler_state["scale"] if scaler is not None else None
            if acc == 1:
                loss_data, grads, new_buffers = fwd_bwd(params, buffers, frozen, key, batch, scale)
            else:
                # micro-batch split: arrays sharing the batch leading dim are
                # scanned [acc, B/acc, ...]; everything else replicates
                bdim = jnp.shape(batch[0])[0] if batch else 0
                split = [
                    hasattr(b, "shape") and jnp.ndim(b) >= 1 and b.shape[0] == bdim and bdim % acc == 0
                    for b in batch
                ]
                if not any(split):
                    raise ValueError(
                        f"accumulate_steps={acc}: no batch array with leading dim divisible by {acc}"
                    )
                xs = tuple(
                    b.reshape(acc, b.shape[0] // acc, *b.shape[1:]) if s else None
                    for b, s in zip(batch, split)
                )
                keys = jax.random.split(key, acc)

                def micro(carry, x):
                    gacc, buf_c, loss_acc = carry
                    mkey, micro_xs = x
                    micro_batch = tuple(
                        (m if s else b) for m, b, s in zip(micro_xs, batch, split)
                    )
                    loss_m, grads_m, buf_n = fwd_bwd(params, buf_c, frozen, mkey, micro_batch, scale)
                    gacc = {
                        k: gacc[k] + grads_m[k].astype(jnp.float32) for k in gacc
                    }
                    return (gacc, buf_n, loss_acc + loss_m.astype(jnp.float32)), None

                # trace one micro to learn the grad structure (shapes static)
                g0 = jax.eval_shape(
                    lambda p, b, f, kk, bb: fwd_bwd(p, b, f, kk, bb, scale)[1],
                    params, buffers, frozen, keys[0],
                    tuple(x[0] if s else b for x, b, s in zip(xs, batch, split)),
                )
                gacc0 = {k: jnp.zeros(v.shape, jnp.float32) for k, v in g0.items()}
                (gsum, new_buffers, loss_sum), _ = jax.lax.scan(
                    micro, (gacc0, buffers, jnp.float32(0)), (keys, xs)
                )
                grads = {k: v / acc for k, v in gsum.items()}
                loss_data = loss_sum / acc
            if scaler is not None:
                grads = {k: g / scaler_state["scale"] for k, g in grads.items()}

            skip = None
            new_scaler_state = scaler_state
            finite_grads = None
            if scaler is not None or nf_state is not None:
                finite_grads = jnp.all(
                    jnp.stack([jnp.all(jnp.isfinite(g.astype(jnp.float32))) for g in grads.values()])
                ) if grads else jnp.asarray(True)
            if scaler is not None:
                skip = ~finite_grads
                new_scaler_state = scaler.update_state(scaler_state, finite_grads)
            new_nf_state = nf_state
            if nf_state is not None:
                # non-finite sentinel: a NaN/Inf loss or gradient skips the
                # whole update IN-PROGRAM (params, slots and opt step all
                # hold), and the device-resident counters let the host
                # detect a model that stopped producing finite steps
                nf_skip = ~(finite_grads
                            & jnp.all(jnp.isfinite(loss_data.astype(jnp.float32))))
                skip = nf_skip if skip is None else (skip | nf_skip)
                new_nf_state = {
                    "consec": jnp.where(nf_skip, nf_state["consec"] + 1,
                                        0).astype(jnp.int32),
                    "total": nf_state["total"] + nf_skip.astype(jnp.int32),
                }

            # dynamics reads the UNSCALED pre-clip gradients (what the
            # model actually produced); the update side brackets the
            # optimizer below, so ||delta_w|| reflects clip/decay/skip
            raw_grads = grads
            with jax.named_scope("optimizer"):
                if opt._grad_clip is not None:
                    pg = [(Tensor(params[k]), Tensor(g)) for k, g in grads.items()]
                    pg = opt._grad_clip(pg)
                    grads = {k: t._data for (k, _), (_, t) in zip(grads.items(), pg)}

                new_params, new_opt_state = opt.apply_gradients(params, grads, opt_state, lr, skip_update=skip)
            new_dyn_state = dyn_state
            if dyn_state is not None:
                with jax.named_scope("dynamics"):
                    new_dyn_state = dyn.update(dyn_state, loss_data,
                                               raw_grads, params, new_params)
            return (loss_data, new_params, new_buffers, new_opt_state,
                    new_scaler_state, new_nf_state, new_dyn_state)

        self._step_fn = step_fn
        self._compiled = self._compile(step_fn)
        self._compiled_multi = {}  # n -> jitted scan-of-step program
        # HBM budget ledger (ISSUE 8): params + optimizer state become
        # weakly-bound byte providers — the silent bf16->f32 Adam upcast
        # class of regression shows up in device.hbm_component_bytes
        # instead of as an unexplained RESOURCE_EXHAUSTED
        _compilemem.memory.register_component_provider(
            "params", self, "_hbm_params_bytes")
        _compilemem.memory.register_component_provider(
            "optimizer", self, "_hbm_optimizer_bytes")

    def _hbm_params_bytes(self):
        return _compilemem.tree_nbytes(
            [p._data for p in self._trainable.values()]
            + [p._data for p in self._frozen.values()]
            + [b._data for b in self._buffers.values()])

    def _hbm_optimizer_bytes(self):
        return _compilemem.tree_nbytes([self.opt_state, self._scaler_state])

    def _compile(self, step_fn):
        # ONE logical program: recompiles mean the input signature
        # drifted, which is exactly what the churn detector watches
        return _compilemem.ledgered_jit(
            step_fn, key="train.step", donate_argnums=(0, 1, 3, 4, 5, 6))

    def _multi_fn(self, n, stacked):
        """Pure n-steps-in-one-program function (lax.scan over the step
        body). One host→device dispatch per n steps instead of per step — on
        dispatch-latency-heavy links (the axon tunnel measures
        ~1.3 s/dispatch) this is the difference between measuring the link
        and measuring the chip. lr is held constant across the n steps
        (scheduler ticks once per call). stacked=True scans a [n, ...]-leading
        batch (a different micro-batch per step)."""
        step_fn = self._step_fn

        def multi_fn(params, buffers, frozen, opt_state, scaler_state,
                     nf_state, dyn_state, lr, key, batch):
            def body(carry, x):
                p, b, o, s, nf, dy = carry
                k, step_batch = (x, batch) if not stacked else x
                loss, p2, b2, o2, s2, nf2, dy2 = step_fn(
                    p, b, frozen, o, s, nf, dy, lr, k, step_batch)
                return (p2, b2, o2, s2, nf2, dy2), loss

            keys = jax.random.split(key, n)
            xs = (keys, batch) if stacked else keys
            (p, b, o, s, nf, dy), losses = jax.lax.scan(
                body, (params, buffers, opt_state, scaler_state, nf_state,
                       dyn_state), xs
            )
            return losses, p, b, o, s, nf, dy

        return multi_fn

    def _compile_multi(self, n, stacked):
        # (n, stacked) are intended program variants — each gets its own
        # ledger key so a legitimate multi-bucket run is not churn
        return _compilemem.ledgered_jit(
            self._multi_fn(n, stacked),
            key=f"train.multi[n={n},stacked={stacked}]",
            donate_argnums=(0, 1, 3, 4, 5, 6))

    def run_steps(self, *batch, n, stacked=False):
        """Run n optimizer steps in a single device dispatch. With
        stacked=False each batch array is reused for every step; with
        stacked=True each batch array carries a leading [n] dim — one
        micro-batch per step, real training in one dispatch. Returns the [n]
        per-step loss array (device-resident until read)."""
        key = (n, stacked)
        cold = key not in self._compiled_multi
        if cold:
            self._compiled_multi[key] = self._compile_multi(n, stacked)
            # the formerly-unbounded program cache (ISSUE 8 satellite):
            # size exported per cache, warn past the configured bound
            _compilemem.ledger.note_cache_size(
                "train.multi", len(self._compiled_multi))
        params = {k: p._data for k, p in self._trainable.items()}
        buffers = {k: b._data for k, b in self._buffers.items()}
        frozen = {k: p._data for k, p in self._frozen.items()}
        lr = self.optimizer.get_lr()
        batch_data = tuple(to_tensor(b)._data for b in batch)
        if stacked:
            self._check_stacked(batch_data, n)
        _dp = _devprof._PLANE
        t0 = _time.monotonic() if _dp is not None else 0.0
        try:
            chaos.site("obs.oom")
            (losses, new_params, new_buffers, self.opt_state,
             self._scaler_state, self._nf_state, self._dyn_state) = (
                self._compiled_multi[key](
                    params, buffers, frozen, self.opt_state, self._scaler_state,
                    self._nf_state, self._dyn_state, lr, prandom.next_key(),
                    batch_data,
                )
            )
        except Exception as e:
            _compilemem.maybe_oom_report(e, program="train.multi")
            raise
        if _dp is not None and not cold:
            # cold dispatches include the compile and would poison the
            # device-time table; the losses buffer completes with the
            # program, so waiting on it times the whole n-step dispatch
            _dp.tick(f"train.multi[n={n},stacked={stacked}]", t0, losses,
                     context="train")
        return self._finish_run_steps(losses, new_params, new_buffers, n)

    def _finish_run_steps(self, losses, new_params, new_buffers, n):
        """Shared run_steps epilogue (also used by DistributedTrainStep):
        write back state and keep the LR schedule ALIGNED — the dispatch ran
        n optimizer steps at the dispatch-start LR (schedule granularity is
        per dispatch), so the scheduler must tick n times, landing on the
        same schedule position as n sequential step() calls."""
        for k, v in new_params.items():
            self._trainable[k]._data = v
        for k, v in new_buffers.items():
            self._buffers[k]._data = v
        _bump_mutation_version()  # direct rebinds must invalidate weight caches
        sched = self.optimizer._learning_rate_scheduler
        if sched is not None:
            for _ in range(n):
                sched.step()
        self.optimizer._global_step += n
        _watchdog.maybe_beat(self.optimizer._global_step)
        # one dispatch covered n steps — always worth the one host read
        self._nf_check(force=True)
        # dynamics stays CADENCE-gated (counting the n covered steps):
        # forcing a spill here would put a device sync inside every
        # multi-step dispatch — exactly what bench.py's timed scan rungs
        # must not pay (they force their own spill after timing)
        self._dyn_check(n=n)
        # one dispatch covered n TRAIN steps: the capture contract counts
        # steps, so the tick burns n, not 1
        _flightrec.maybe_capture_step(self.optimizer._global_step, n=n)
        return Tensor(losses)

    def _nf_check(self, force=False):
        """Host side of the non-finite sentinel: read the device-resident
        skip counters every ``PADDLE_NONFINITE_CHECK_EVERY`` dispatches
        (the read synchronizes on the step, so it is cadence-gated), bump
        ``train.nonfinite_skips`` by the delta, and raise
        :class:`NonFiniteLossError` once the CONSECUTIVE count reaches the
        tolerance. The consecutive counter only grows while skipping, so a
        stuck model is always detected within one cadence window; a
        transient blip that recovers before the read was harmless by
        construction (every skipped update left the weights untouched)."""
        if self._nf_state is None:
            return
        self._nf_since_check += 1
        if not force and self._nf_since_check < self._nf_check_every:
            return
        self._nf_since_check = 0
        # the counter read synchronizes on the step: explicit goodput
        # phase, never silently folded into step time (ISSUE 13 satellite)
        with _goodput.account("telemetry"):
            total = int(self._nf_state["total"])
            consec = int(self._nf_state["consec"])
        if total > self._nf_reported:
            _registry.counter("train.nonfinite_skips").inc(
                total - self._nf_reported)
            self._nf_reported = total
            # non-finite provenance (ISSUE 13): the dynamics carry knows
            # WHICH layer group went NaN/Inf first — attach it to the
            # flight-record bundle (rate-limited: a skip storm commits one
            # bundle per window, not one per read)
            prov = self._nf_provenance()
            _flightrec.record(
                "nonfinite", step=self.optimizer._global_step,
                payload={"skips_total": total, "consecutive": consec,
                         "tolerance": self._nf_tolerance,
                         "provenance": prov})
        if consec >= self._nf_tolerance:
            from .utils.metrics_bus import counters as _counters

            _counters.bump("fault.train.nonfinite_exhausted")
            prov = self._nf_provenance()
            prov_msg = ""
            if prov:
                prov_msg = (
                    f"; first non-finite gradients in layer group(s) "
                    f"{', '.join(prov['first_groups']) or '<loss only>'} "
                    f"at update {prov['first_update']} "
                    f"(currently non-finite: "
                    f"{', '.join(prov['current_groups']) or '<loss only>'})")
            raise NonFiniteLossError(
                f"loss/grads non-finite for {consec} consecutive steps "
                f"(tolerance {self._nf_tolerance}, "
                f"{total} skipped updates total, global step "
                f"{self.optimizer._global_step}){prov_msg} — every skipped "
                f"update left the weights uncorrupted; lower the LR / "
                f"check the data, or raise {NONFINITE_TOLERANCE_ENV}")

    def _nf_provenance(self):
        """The dynamics carry's latched which-group-went-non-finite-first
        record (None when dynamics is off or everything stayed finite)."""
        if self._dynamics is None:
            return None
        with _goodput.account("telemetry"):
            return self._dynamics.provenance(self._dyn_state)

    def _dyn_check(self, force=False, n=1):
        """Host side of the dynamics telemetry: once per
        ``PADDLE_DYNAMICS_EVERY_STEPS`` covered steps (the read
        synchronizes on the step, so it is cadence-gated like the nf
        counters; a run_steps dispatch counts its n steps), spill the
        carry — publish the train.* gauges, extend the flight window,
        fire the loss-spike trigger. Between spills this is one counter
        increment; disabled it is the is-None check above."""
        if self._dynamics is None:
            return
        self._dyn_since_check += n
        if not force and self._dyn_since_check < self._dynamics.every:
            return
        self._dyn_since_check = 0
        with _goodput.account("telemetry"):
            self._dynamics.spill(self._dyn_state,
                                 step=self.optimizer._global_step)
            # re-arm the per-window max-z latch: each window reports its
            # own worst spike
            self._dyn_state = self._dynamics.reset_window(self._dyn_state)

    @staticmethod
    def _check_stacked(batch_data, n):
        import numpy as np

        for b in batch_data:
            if np.shape(b)[0] != n:
                raise ValueError(
                    f"stacked run_steps: leading dim {np.shape(b)[0]} != n={n}")

    def __call__(self, *batch):
        first = not self._dispatched
        with _tracing.span("train.step"), \
                _goodput.account("init" if first else "step"):
            with _tracing.span("train.step.host_prep"):
                params = {k: p._data for k, p in self._trainable.items()}
                buffers = {k: b._data for k, b in self._buffers.items()}
                frozen = {k: p._data for k, p in self._frozen.items()}
                lr = self.optimizer.get_lr()
                batch_data = tuple(to_tensor(b)._data for b in batch)
            with _tracing.span("train.step.dispatch"):
                # OOM-forensics seam (ISSUE 8): a RESOURCE_EXHAUSTED out
                # of the dispatch commits telemetry/oom_report.json before
                # re-raising; the obs.oom chaos site injects one
                # deterministically for tests
                _dp = _devprof._PLANE
                t0 = _time.monotonic() if _dp is not None else 0.0
                try:
                    chaos.site("obs.oom")
                    (loss, new_params, new_buffers, self.opt_state,
                     self._scaler_state, self._nf_state,
                     self._dyn_state) = self._compiled(
                        params, buffers, frozen, self.opt_state,
                        self._scaler_state, self._nf_state, self._dyn_state,
                        lr, prandom.next_key(), batch_data
                    )
                except Exception as e:
                    _compilemem.maybe_oom_report(e, program="train.step")
                    raise
                if _dp is not None and not first:
                    # first dispatch includes the XLA compile; the loss
                    # buffer completes with the fused program, so waiting
                    # on it times the full step's device execution
                    _dp.tick("train.step", t0, loss, context="train")
        self._dispatched = True
        # write state back into the dygraph objects
        for k, v in new_params.items():
            self._trainable[k]._data = v
        for k, v in new_buffers.items():
            self._buffers[k]._data = v
        _bump_mutation_version()  # direct rebinds must invalidate weight caches
        sched = self.optimizer._learning_rate_scheduler
        if sched is not None:
            sched.step()
        self.optimizer._global_step += 1
        _watchdog.maybe_beat(self.optimizer._global_step)
        self._nf_check()
        self._dyn_check()
        _flightrec.maybe_capture_step(self.optimizer._global_step)
        if self.metrics_bus is not None:
            if self.metrics_bus.tokens_per_step is None and batch_data:
                import math

                self.metrics_bus.tokens_per_step = int(math.prod(batch_data[0].shape))
            self.metrics_bus.on_step(loss=loss)
        return Tensor(loss)
