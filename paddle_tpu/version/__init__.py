"""paddle.version parity (reference: generated python/paddle/version/__init__.py)."""
full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
commit = "unknown"
istaged = False
with_pip = False

cuda_version = "False"
cudnn_version = "False"
tensorrt_version = "False"
xpu_version = "False"


def show():
    print(f"full_version: {full_version} (TPU-native build; XLA is the compiler)")


def cuda():
    return "False"


def cudnn():
    return "False"


def tensorrt():
    return "False"


def xpu():
    return "False"
