"""paddle.save / paddle.load parity (reference: python/paddle/framework/io.py).

Format: pickle of nested containers with tensors materialized as numpy arrays
(bfloat16 kept via ml_dtypes). A restricted unpickler guards load, mirroring
the reference's safe-unpickler concern.
"""
import io
import os
import pickle

import numpy as np

from .framework.core import Parameter, Tensor
from .testing import chaos


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data), "param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("param") else Tensor
            return cls(obj["data"])
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_storable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


_SAFE_MODULES = {"numpy", "numpy.core.multiarray", "numpy._core.multiarray", "ml_dtypes", "collections"}


class _SafeUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        root = module.split(".")[0]
        if root in ("numpy", "ml_dtypes", "collections", "builtins"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(f"blocked unpickle of {module}.{name}")


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_to_storable(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # atomic temp+rename: autoresume/ModelCheckpoint overwrite the SAME path
    # every save — a trainer killed mid-write (preemption) must leave the
    # previous checkpoint loadable, never a torn pickle at the final name
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_to_storable(obj), f, protocol=protocol)
            f.flush()
            os.fsync(f.fileno())
        chaos.site("save.write", path=tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load(path, return_numpy=False, **configs):
    if hasattr(path, "read"):
        raw = _SafeUnpickler(path).load()
    else:
        with open(path, "rb") as f:
            raw = _SafeUnpickler(f).load()
    return _from_storable(raw, return_numpy=return_numpy)
