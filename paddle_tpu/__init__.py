"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Architecture (SURVEY.md §7): XLA replaces the reference's kernel library,
executor, and compiler (Phi/InterpreterCore/CINN); this package supplies the
imperative user API (Tensor/Layer/Optimizer/AMP/DataLoader), the parallelism
orchestration (mesh, fleet, TP/PP/ZeRO/SP/EP, auto-parallel), Pallas kernels
for the hot paths, and the launcher/checkpoint/profiler shell.
"""
from . import framework
from .framework import dtype as _dtype_mod
from .framework.core import Parameter, Tensor, no_grad, to_tensor
from .framework.dtype import (
    bfloat16,
    bool,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .framework.param_attr import ParamAttr
from .framework.random import get_rng_state, seed, set_rng_state

from . import tensor
from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation

from . import autograd
from .autograd import grad

from . import nn
from . import optimizer
from . import amp
from . import io
from . import metric
from . import device
from . import jit as jit_mod
from .jit_api import jit, to_static

# `paddle.jit` is both the compile decorator and the jit namespace
jit.to_static = jit_mod.to_static
jit.save = jit_mod.save
jit.load = jit_mod.load
jit.not_to_static = jit_mod.not_to_static
jit.enable_to_static = jit_mod.enable_to_static
jit.ignore_module = jit_mod.ignore_module
jit.TrainStep = jit_mod.TrainStep
from . import vision
from . import hapi
from .hapi import Model
from . import distributed
from . import incubate
from . import distribution
from . import quantization
from . import audio
from . import text
from . import observability
from . import profiler
from . import sparse
from . import linalg as _linalg_ns
from . import fft
from . import signal
from . import inference
from . import serving
from . import static
from .serialization import load, save

linalg = tensor.linalg

CPUPlace = device.CPUPlace
TPUPlace = device.TPUPlace
CUDAPlace = device.TPUPlace  # CUDA-script compat: maps to the TPU device
CUDAPinnedPlace = device.CPUPlace

set_device = device.set_device
get_device = device.get_device
is_compiled_with_cuda = lambda: False
is_compiled_with_xpu = lambda: False
is_compiled_with_rocm = lambda: False
is_compiled_with_cinn = lambda: False
is_compiled_with_custom_device = lambda name="tpu": name == "tpu"
is_compiled_with_tpu = lambda: True
in_dynamic_mode = lambda: not static.in_static_mode()
in_dynamic_or_pir_mode = in_dynamic_mode

disable_static = static.disable_static
enable_static = static.enable_static

DataParallel = None  # installed by paddle_tpu.distributed at import time


def _install_dataparallel():
    global DataParallel
    from .distributed.parallel import DataParallel as _DP

    DataParallel = _DP


_install_dataparallel()

disable_signal_handler = lambda: None

from .framework.flags import get_flags, set_flags  # noqa: E402

from . import regularizer
from . import utils
from . import version
from . import hub
from .hapi import callbacks

__version__ = version.full_version
base = framework  # paddle.base compat alias (reference: python/paddle/base)


def iinfo(dtype):
    import numpy as np

    return np.iinfo(np.dtype(_dtype_mod.convert_dtype(dtype)))


def finfo(dtype):
    import jax.numpy as jnp
    import ml_dtypes
    import numpy as np

    d = _dtype_mod.convert_dtype(dtype)
    return ml_dtypes.finfo(d) if d == jnp.bfloat16 else np.finfo(np.dtype(d))


def batch(reader, batch_size, drop_last=False):
    """legacy paddle.batch reader decorator."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


class LazyGuard:
    """reference: paddle.LazyGuard — delayed param init. Params here are
    cheap host arrays until first use, so this is a no-op guard."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class onnx:  # namespace stub (reference: paddle.onnx.export via paddle2onnx)
    @staticmethod
    def export(*a, **k):
        raise NotImplementedError(
            "ONNX export is not part of the TPU-native build; export via "
            "paddle_tpu.jit.save (weights) or AOT-compile with jax.export"
        )



def set_grad_enabled(flag):
    """Applies immediately (paddle semantics); also usable as a context
    manager that restores the previous mode on exit."""
    from .framework import core as _core

    prev = _core._grad_enabled()
    _core._tls.grad_enabled = flag

    class _Guard:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _core._tls.grad_enabled = prev

    return _Guard()


def is_grad_enabled():
    from .framework import core as _core

    return _core._grad_enabled()


def enable_grad(func=None):
    """reference: paddle.enable_grad — context manager (or decorator)
    forcing gradient tracking on, e.g. inside a no_grad region."""
    guard = set_grad_enabled(True)
    if func is None:
        return guard
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with set_grad_enabled(True):
            return func(*args, **kwargs)

    guard.__exit__()
    return wrapper


def summary(net, input_size=None, dtypes=None, input=None):
    return hapi.summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0


__version__ = "0.1.0"
