"""Static-graph compatibility layer (reference: python/paddle/static/).

On TPU, "static mode" IS jax.jit — the traced program is the Program and XLA
is the executor (reference: Program/Executor/InterpreterCore in
paddle/fluid/framework/new_executor/, which SURVEY.md §3.5 maps to XLA).
This module keeps the script-level API (enable_static, Executor, data) as a
thin veneer: programs are recorded as traced python callables.
"""
import jax

from ..framework.core import Tensor, to_tensor

_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_static_mode():
    return _static_mode


class Program:
    def __init__(self):
        self._fns = []
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


def program_guard(main_program, startup_program=None):
    import contextlib

    return contextlib.nullcontext()


class InputSpec:
    """paddle.static.InputSpec parity — shape/dtype/name spec used by
    jit.to_static and hapi.Model."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        # static programs are python callables under jit in this framework
        if callable(program):
            out = program(**{k: to_tensor(v) for k, v in (feed or {}).items()})
            return out if isinstance(out, (list, tuple)) else [out]
        raise NotImplementedError(
            "Executor.run over legacy Program objects is not supported; use "
            "paddle_tpu.jit.to_static-compiled callables (XLA is the executor)"
        )
