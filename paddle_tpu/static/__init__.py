"""Static-graph compatibility layer (reference: python/paddle/static/ —
Program/Executor/InterpreterCore in paddle/fluid/framework/new_executor/).

On TPU, "static mode" IS lazy tracing + XLA execution. This module makes the
classic script workflow REAL, not a veneer:

    paddle.enable_static()
    x = paddle.static.data("x", [None, 8])      # symbolic Variable
    y = paddle.mean(paddle.nn.functional.relu(x @ w))   # ops RECORD, not run
    exe = paddle.static.Executor()
    (out,) = exe.run(feed={"x": arr}, fetch_list=[y])   # evaluates the graph

Mechanics: `data()` returns a symbolic `Variable`; `framework.core.apply`
detects symbolic inputs and records the op (fn + input refs) into the
default Program instead of executing, with shapes inferred via
jax.eval_shape. `Executor.run` memo-evaluates the recorded graph on the
feeds (each fetch set is jit-compiled and cached on the Program).

Scope: forward graphs. `append_backward`-style static autodiff is NOT
supported — training uses the dygraph TrainStep (one jit with tape
backward), which subsumes it on this substrate.
"""
import itertools

import jax
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, init_tensor_slots, to_tensor
from ..observability import compilemem as _compilemem

_static_mode = False
_var_counter = itertools.count()


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_static_mode():
    return _static_mode


class _Op:
    """One recorded op: raw-array fn + ordered inputs (Variables or
    concrete Tensors closed over as constants)."""

    __slots__ = ("fn", "inputs", "n_outputs")

    def __init__(self, fn, inputs, n_outputs):
        self.fn = fn
        self.inputs = inputs
        self.n_outputs = n_outputs


class Variable(Tensor):
    """Symbolic static-graph tensor: shape/dtype known (−1 = dynamic),
    no data until Executor.run."""

    _is_static_var = True

    def __init__(self, name=None, shape=(), dtype="float32", op=None, out_idx=0):
        init_tensor_slots(self, name=name or f"tmp_{next(_var_counter)}")
        self._shape = [-1 if s is None else int(s) for s in shape]
        self._dtype = dtypes.convert_dtype(dtype) if isinstance(dtype, str) else dtype
        self._op = op
        self._op_out = out_idx

    @property
    def _data(self):
        raise TypeError(
            f"static Variable '{self.name}' has no data — run it through "
            "paddle.static.Executor().run(feed=..., fetch_list=[...])"
        )

    @_data.setter
    def _data(self, v):  # pragma: no cover — defensive
        raise TypeError("static Variables are symbolic; cannot assign data")

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self._shape}, dtype={self._dtype})"


def record_static_op(fn, tensors, name=""):
    """Called by framework.core.apply when any input is symbolic: infer
    output shapes abstractly and append the op to the default Program."""
    def abstracts(dyn_sub):
        out = []
        for t in tensors:
            if getattr(t, "_is_static_var", False):
                shape = tuple(dyn_sub if s == -1 else s for s in t._shape)
                out.append(jax.ShapeDtypeStruct(shape, t._dtype))
            else:
                out.append(jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype))
        return out

    # probe with two different substitutions for dynamic dims: output dims
    # that move with the substitution are themselves dynamic (-1)
    has_dynamic = any(
        getattr(t, "_is_static_var", False) and -1 in t._shape for t in tensors
    )
    out = jax.eval_shape(fn, *abstracts(1))
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    if has_dynamic:
        out2 = jax.eval_shape(fn, *abstracts(2))
        outs2 = list(out2) if multi else [out2]
        outs = [
            jax.ShapeDtypeStruct(
                tuple(-1 if d1 != d2 else d1 for d1, d2 in zip(o1.shape, o2.shape)),
                o1.dtype,
            )
            for o1, o2 in zip(outs, outs2)
        ]
    op = _Op(fn, list(tensors), len(outs))
    prog = default_main_program()
    vars_ = [
        Variable(name=f"{name or 'op'}_{next(_var_counter)}",
                 shape=o.shape, dtype=o.dtype, op=op, out_idx=i)
        for i, o in enumerate(outs)
    ]
    prog._vars.extend(vars_)
    return type(out)(vars_) if multi else vars_[0]


class Program:
    def __init__(self):
        self._vars = []
        self._inputs = {}
        self._exec_cache = {}
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def list_vars(self):
        return list(self._inputs.values()) + list(self._vars)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    """Swap the default main/startup programs for the `with` body
    (reference: static.program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        global _default_main, _default_startup
        self._prev = (_default_main, _default_startup)
        _default_main = self._main
        if self._startup is not None:
            _default_startup = self._startup
        return self._main

    def __exit__(self, *exc):
        global _default_main, _default_startup
        _default_main, _default_startup = self._prev
        return False


class InputSpec:
    """paddle.static.InputSpec parity — shape/dtype/name spec used by
    jit.to_static and hapi.Model."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)


def data(name, shape, dtype="float32", lod_level=0):
    """In static mode: a symbolic graph input registered on the default
    Program. In dygraph mode: an InputSpec (the to_static contract)."""
    if not _static_mode:
        return InputSpec(shape, dtype, name)
    v = Variable(name=name, shape=shape, dtype=dtype)
    default_main_program()._inputs[name] = v
    return v


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        # to_static-compiled callables execute directly
        if callable(program) and not isinstance(program, Program):
            out = program(**{k: to_tensor(v) for k, v in (feed or {}).items()})
            return out if isinstance(out, (list, tuple)) else [out]
        program = program if program is not None else default_main_program()
        fetch_list = fetch_list or []
        if not fetch_list:
            return []  # startup programs have nothing to compute here
        feed = {k: to_tensor(v)._data for k, v in (feed or {}).items()}

        # one jitted evaluator per (fetch set, feed signature), cached
        key = (tuple(id(f) for f in fetch_list),
               tuple(sorted((k, tuple(v.shape), str(v.dtype)) for k, v in feed.items())))
        runner = program._exec_cache.get(key)
        if runner is None:
            def evaluate(feed_arrays):
                memo = {}

                def ev(v):
                    if not getattr(v, "_is_static_var", False):
                        return v._data
                    if v._op is None:
                        if v.name not in feed_arrays:
                            raise KeyError(
                                f"Executor.run: feed missing input '{v.name}'")
                        return feed_arrays[v.name]
                    if id(v._op) not in memo:
                        args = [ev(t) for t in v._op.inputs]
                        out = v._op.fn(*args)
                        memo[id(v._op)] = out if isinstance(out, (tuple, list)) else (out,)
                    return memo[id(v._op)][v._op_out]

                return [ev(f) for f in fetch_list]

            runner = program._exec_cache[key] = _compilemem.ledgered_jit(
                evaluate,
                key=f"static.exec[prog{id(program) & 0xffff:x},"
                    f"fetch{len(fetch_list)}]")
            _compilemem.ledger.note_cache_size(
                "static.exec", len(program._exec_cache))
        outs = runner(feed)
        return [np.asarray(o) for o in outs]


def _graph_fn(fetch_list):
    """The recorded graph as a pure fn of {feed name: array} (the same
    memo-evaluator Executor.run jits, factored for export)."""

    def evaluate(feed_arrays):
        memo = {}

        def ev(v):
            if not getattr(v, "_is_static_var", False):
                return v._data
            if v._op is None:
                if v.name not in feed_arrays:
                    raise KeyError(f"feed missing input '{v.name}'")
                return feed_arrays[v.name]
            if id(v._op) not in memo:
                args = [ev(t) for t in v._op.inputs]
                out = v._op.fn(*args)
                memo[id(v._op)] = out if isinstance(out, (tuple, list)) else (out,)
            return memo[id(v._op)][v._op_out]

        return tuple(ev(f) for f in fetch_list)

    return evaluate


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **configs):
    """reference: static.save_inference_model (serialized Program +
    persistables). TPU-native artifact: the recorded feed→fetch graph is
    traced and exported as StableHLO (jax.export) with weights baked in as
    constants; dynamic dims (-1) become SYMBOLIC dimensions — dim 0 shares
    one "batch" symbol across feeds, other dynamic dims get their own — so
    the loaded artifact serves any batch size without retracing."""
    import json

    from jax import export as jexport

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]

    extra = itertools.count()
    scopes = {}

    def aval(v):
        dims = []
        for i, s in enumerate(v._shape):
            if s == -1:
                sym = "batch" if i == 0 else f"d{next(extra)}"
                dims.append(sym)
            else:
                dims.append(str(s))
        shape = jexport.symbolic_shape(",".join(dims), scope=scopes.setdefault("s", jexport.SymbolicScope()))
        return jax.ShapeDtypeStruct(tuple(shape), v._dtype)

    feeds = {v.name: aval(v) for v in feed_vars}
    # AOT export site: jexport.export needs the raw jit-wrapped callable,
    # so the ledger brackets the whole trace+lower explicitly
    with _compilemem.record_compile("static.export", trigger="aot"):
        exp = jexport.export(jax.jit(_graph_fn(fetch_vars)))(feeds)  # compile-ledger-ok
    header = {
        "feed": [
            {"name": v.name, "shape": v._shape, "dtype": str(np.dtype(v._dtype))}
            for v in feed_vars
        ],
        "fetch": [v.name for v in fetch_vars],
    }
    blob = json.dumps(header).encode() + b"\n" + exp.serialize()
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(blob)
    return path_prefix + ".pdmodel"


class _LoadedInferenceProgram:
    """Callable returned by load_inference_model; Executor.run routes
    callables here: program(**{name: Tensor}) -> [np.ndarray, ...]."""

    def __init__(self, exp, feed_names, fetch_names):
        self._exp = exp
        self.feed_target_names = feed_names
        self.fetch_names = fetch_names

    def __call__(self, **feed):
        arrays = {k: to_tensor(v)._data for k, v in feed.items()}
        missing = [n for n in self.feed_target_names if n not in arrays]
        if missing:
            raise KeyError(f"load_inference_model program: feed missing {missing}")
        outs = self._exp.call({n: arrays[n] for n in self.feed_target_names})
        return [np.asarray(o) for o in outs]


def load_inference_model(path_prefix, executor=None, **configs):
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; `program` is the deserialized StableHLO artifact wrapped as
    a callable Executor.run understands."""
    import json

    from jax import export as jexport

    with open(path_prefix + ".pdmodel", "rb") as f:
        blob = f.read()
    nl = blob.index(b"\n")
    header = json.loads(blob[:nl].decode())
    exp = jexport.deserialize(bytearray(blob[nl + 1:]))
    prog = _LoadedInferenceProgram(exp, [d["name"] for d in header["feed"]],
                                   header["fetch"])
    return [prog, prog.feed_target_names, prog.fetch_names]
