"""paddle.signal parity (reference: python/paddle/signal.py — stft/istft over
frame/overlap_add ops in phi/kernels/frame_kernel.*).

All ops route through framework.core.apply so they record tape nodes and
gradients flow to the input signal (and window), matching the reference's
differentiable signal ops.
"""
import jax.numpy as jnp

from .framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along `axis` (reference: signal.frame)."""

    def fn(xd):
        moved = axis not in (-1, xd.ndim - 1)
        if moved:
            xd = jnp.moveaxis(xd, axis, -1)
        n_frames = 1 + (xd.shape[-1] - frame_length) // hop_length
        idx = jnp.arange(frame_length)[None, :] + hop_length * jnp.arange(n_frames)[:, None]
        out = xd[..., idx]  # [..., n_frames, frame_length]
        out = jnp.swapaxes(out, -1, -2)  # paddle layout: [..., frame_length, n_frames]
        if moved:
            out = jnp.moveaxis(out, -1, axis)
        return out

    return apply(fn, _t(x), name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference: signal.overlap_add). x: [..., frame_length,
    n_frames] (axis=-1) → [..., output_len]."""

    def fn(xd):
        if axis not in (-1, xd.ndim - 1):
            xd = jnp.moveaxis(xd, axis, -1)
        frame_length, n_frames = xd.shape[-2], xd.shape[-1]
        out_len = frame_length + hop_length * (n_frames - 1)
        batch = xd.shape[:-2]
        out = jnp.zeros(batch + (out_len,), xd.dtype)
        idx = jnp.arange(frame_length)[None, :] + hop_length * jnp.arange(n_frames)[:, None]
        # scatter-add each frame at its offset
        return out.at[..., idx].add(jnp.swapaxes(xd, -1, -2))

    return apply(fn, _t(x), name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """reference: paddle.signal.stft. x: [B, T] or [T]. Returns complex
    [B, n_fft//2+1, n_frames] (onesided)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(xd, win):
        win = win.astype(jnp.float32)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        if center:
            pad = n_fft // 2
            xd = jnp.pad(xd, [(0, 0)] * (xd.ndim - 1) + [(pad, pad)], mode=pad_mode)
        n_frames = 1 + (xd.shape[-1] - n_fft) // hop_length
        idx = jnp.arange(n_fft)[None, :] + hop_length * jnp.arange(n_frames)[:, None]
        frames = xd[..., idx] * win  # [..., n_frames, n_fft]
        if onesided:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.moveaxis(spec, -1, -2)

    win_t = _t(window) if window is not None else Tensor(
        jnp.ones(win_length, jnp.float32), stop_gradient=True
    )
    return apply(fn, _t(x), win_t, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False, name=None):
    """reference: paddle.signal.istft — WOLA reconstruction."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(sd, win):
        win = win.astype(jnp.float32)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        spec = jnp.moveaxis(sd, -2, -1)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        n_frames = frames.shape[-2]
        out_len = n_fft + hop_length * (n_frames - 1)
        batch = frames.shape[:-2]
        out = jnp.zeros(batch + (out_len,), frames.dtype)
        idx = jnp.arange(n_fft)[None, :] + hop_length * jnp.arange(n_frames)[:, None]
        out = out.at[..., idx].add(frames)
        # WOLA normalization: divide by summed squared window
        wsq = jnp.zeros(out_len, jnp.float32).at[idx.reshape(-1)].add(
            jnp.tile(win**2, n_frames)
        )
        out = out / jnp.maximum(wsq, 1e-10)
        if center:
            pad = n_fft // 2
            out = out[..., pad:-pad] if out.shape[-1] > 2 * pad else out
        if length is not None:
            out = out[..., :length]
        return out

    win_t = _t(window) if window is not None else Tensor(
        jnp.ones(win_length, jnp.float32), stop_gradient=True
    )
    return apply(fn, _t(x), win_t, name="istft")
