"""Autoregressive generation (reference: PaddleNLP GenerationMixin.generate +
paddle/fluid/inference decode loop; TPU-native: ONE jitted program — prefill
fills a fixed-shape KV cache via dynamic_update_slice, the decode loop is a
lax.scan (static trip count, static shapes — XLA requirements), greedy or
temperature sampling via jax.random.categorical).

The cache never reallocates: [B, S0b + max_new_tokens, kv_heads, head_dim]
per layer, written at the running position. Prompt lengths are BUCKETED to
powers of two (min 16): the compiled program is keyed on the bucket, takes
the true length as a dynamic scalar, and right-pads the prompt — so serving
compiles O(log S) variants, not one per prompt length. PAPERS.md
ragged-paged-attention is the multi-tenant serving upgrade path.
"""
import jax
import jax.numpy as jnp

from .framework.core import Tensor, to_tensor
from .observability import compilemem as _compilemem

_MIN_BUCKET = 16


def prompt_bucket(s0):
    """Smallest power-of-two bucket >= s0 (floor _MIN_BUCKET)."""
    b = _MIN_BUCKET
    while b < s0:
        b *= 2
    return b


def _make_sampler(do_sample, temperature, top_k, top_p, repetition_penalty,
                  min_length, eos_token_id):
    """ONE sampling fn shared by the dense and ragged builders (greedy /
    temperature / top-k / top-p, CTRL-style repetition penalty over the
    seen-token mask, eos suppression below min_length)."""

    def sample(logits, key, seen=None, n_generated=0):
        logits = logits.astype(jnp.float32)
        if repetition_penalty != 1.0 and seen is not None:
            pen = jnp.where(logits > 0, logits / repetition_penalty,
                            logits * repetition_penalty)
            logits = jnp.where(seen, pen, logits)
        if min_length > 0 and eos_token_id is not None:
            logits = jnp.where(
                (jnp.asarray(n_generated) < min_length)
                & (jnp.arange(logits.shape[-1]) == eos_token_id)[None],
                -jnp.inf, logits,
            )
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / jnp.maximum(temperature, 1e-6)
        if top_k and top_k > 0:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p < 1.0:
            # nucleus: smallest prefix of the sorted distribution reaching
            # top_p mass (the argmax token is always kept)
            srt = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = cum - probs < top_p
            kth_idx = jnp.sum(keep, axis=-1) - 1
            cutoff = jnp.take_along_axis(srt, kth_idx[..., None], axis=-1)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    return sample


def _cache_fwd(m, state, toks, caches, pos, **kw):
    """THE functional_call wrapper every generate builder shares: overrides
    from a raw state dict, fixed-shape KV caches, dynamic cache position."""
    overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
    wrapped = [(Tensor(kc), Tensor(vc)) for kc, vc in caches]
    logits, presents = m.functional_call(
        overrides, Tensor(toks), past_key_values=wrapped,
        cache_position=Tensor(pos), use_cache=True, training=False, **kw,
    )
    return logits._data, tuple((p[0]._data, p[1]._data) for p in presents)


def _prompt_seen_mask(ids, valid, n_vocab):
    """[B, V] bool: tokens present in the VALID prompt positions."""
    B = ids.shape[0]
    return jnp.zeros((B, n_vocab), bool).at[
        jnp.arange(B)[:, None], ids
    ].max(valid)


def _mark_seen(seen, tok):
    return seen if seen is None else seen.at[jnp.arange(seen.shape[0]), tok].set(True)


class GenerationMixin:
    """Mixin for causal LMs whose forward supports
    (input_ids, past_key_values, cache_position, use_cache) -> (logits, caches).
    """

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        if dtype is None:
            if getattr(self, "lm_head", None) is not None:
                dtype = self.lm_head.weight.dtype
            else:
                # model-agnostic probe: cache in the compute dtype of the
                # first parameter (llama tied-embed, GPT wte, ...)
                dtype = next(iter(self.parameters())).dtype
        import numpy as np

        jdt = dtype if not isinstance(dtype, str) else jnp.dtype(dtype)
        shape = (batch_size, max_length, cfg.num_key_value_heads, cfg.head_dim)
        return tuple(
            (jnp.zeros(shape, jdt), jnp.zeros(shape, jdt))
            for _ in range(cfg.num_hidden_layers)
        )

    def generate(self, input_ids, max_new_tokens=32, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, repetition_penalty=1.0, min_length=0,
                 eos_token_id=None, pad_token_id=None, seed=0,
                 decode_strategy=None, num_beams=1, length_penalty=0.0,
                 attention_mask=None):
        """Returns [B, S0 + max_new_tokens] int32 token ids (prompt included).
        After eos, a sequence keeps emitting pad_token_id (defaults to eos).

        decode_strategy (reference: GenerationMixin.generate):
        "greedy_search" (default), "sampling" (≡ do_sample=True), or
        "beam_search" (num_beams > 1, static beam width inside ONE jitted
        scan; length_penalty applies the GNMT ((5+L)/6)^α normalization)."""
        if decode_strategy is None:
            decode_strategy = "sampling" if do_sample else (
                "beam_search" if num_beams > 1 else "greedy_search")
        if decode_strategy == "sampling":
            do_sample = True
        if decode_strategy == "beam_search":
            if num_beams < 2:
                raise ValueError("beam_search needs num_beams >= 2")
            return self._generate_beam(input_ids, max_new_tokens, num_beams,
                                       length_penalty, eos_token_id, pad_token_id)
        if attention_mask is not None:
            return self._generate_ragged(
                input_ids, attention_mask, max_new_tokens, do_sample, temperature,
                top_k, top_p, repetition_penalty, min_length,
                eos_token_id, pad_token_id, seed,
            )
        ids = to_tensor(input_ids)._data.astype(jnp.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        S0b = prompt_bucket(S0)
        cache_key = (B, S0b, max_new_tokens, do_sample, float(temperature), int(top_k),
                     float(top_p), float(repetition_penalty), int(min_length),
                     eos_token_id, pad_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(cache_key)
        if run is None:
            # bucketed program variants are intended: B/S0b live in the
            # ledger key, so a multi-bucket serve is not compile churn
            run = cache[cache_key] = _compilemem.ledgered_jit(
                self._build_generate_fn(B, S0b, max_new_tokens, do_sample, temperature,
                                        top_k, top_p, repetition_penalty, min_length,
                                        eos_token_id, pad_token_id),
                key=f"generate.dense[B{B},S{S0b},n{max_new_tokens}]",
            )
            _compilemem.ledger.note_cache_size("generate", len(cache))
        ids_p = jnp.pad(ids, ((0, 0), (0, S0b - S0)), constant_values=pad_token_id)
        state = self.raw_state_dict()
        gen = run(state, ids_p, jnp.int32(S0), jax.random.PRNGKey(seed))
        return Tensor(jnp.concatenate([ids, gen], axis=1), stop_gradient=True)

    def _generate_ragged(self, input_ids, attention_mask, max_new_tokens, do_sample,
                         temperature, top_k, top_p, repetition_penalty, min_length,
                         eos_token_id, pad_token_id, seed):
        """Per-row prompt lengths in one batch (reference: generate with
        attention_mask over right-padded prompts). The batch is LEFT-aligned
        internally: every row's last real token lands at the same column, so
        the decode loop keeps a single scalar cache position; per-row rope
        positions subtract the pad offset and left-pad cache columns are
        masked out of every attention step."""
        import numpy as np

        ids = np.asarray(to_tensor(input_ids)._data).astype(np.int32)
        mask = np.asarray(to_tensor(attention_mask)._data).astype(np.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        lens = mask.sum(axis=1).astype(np.int32)
        S0b = prompt_bucket(int(lens.max()))
        aligned = np.full((B, S0b), pad_token_id, np.int32)
        for r in range(B):
            # gather by mask, not prefix-slice: callers pad on either side
            aligned[r, S0b - lens[r]:] = ids[r][mask[r].astype(bool)]
        pad_lens = (S0b - lens).astype(np.int32)

        key = ("ragged", B, S0b, max_new_tokens, do_sample, float(temperature),
               int(top_k), float(top_p), float(repetition_penalty), int(min_length),
               eos_token_id, pad_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(key)
        if run is None:
            run = cache[key] = _compilemem.ledgered_jit(
                self._build_ragged_fn(B, S0b, max_new_tokens, do_sample, temperature,
                                      top_k, top_p, repetition_penalty, min_length,
                                      eos_token_id, pad_token_id),
                key=f"generate.ragged[B{B},S{S0b},n{max_new_tokens}]",
            )
            _compilemem.ledger.note_cache_size("generate", len(cache))
        gen = run(self.raw_state_dict(), jnp.asarray(aligned), jnp.asarray(pad_lens),
                  jax.random.PRNGKey(seed))
        return Tensor(jnp.concatenate([jnp.asarray(ids), gen], axis=1),
                      stop_gradient=True)

    def _build_ragged_fn(self, B, S0b, max_new, do_sample, temperature, top_k,
                         top_p, repetition_penalty, min_length,
                         eos_token_id, pad_token_id):
        model = self
        total = S0b + max_new

        def fwd(state, toks, caches, pos, amask, pos_ids):
            return _cache_fwd(model, state, toks, caches, pos,
                              attention_mask=Tensor(amask),
                              position_ids=Tensor(pos_ids))

        sample = _make_sampler(do_sample, temperature, top_k, top_p,
                               repetition_penalty, min_length, eos_token_id)
        use_seen = repetition_penalty != 1.0  # static: no carry cost otherwise

        def run(state, ids, pad_lens, key):
            caches = model.init_cache(B, total)
            # visibility over the FULL cache width: left-pad columns never
            # attendable; future columns handled by the causal position mask
            amask = (jnp.arange(total)[None, :] >= pad_lens[:, None]).astype(jnp.float32)
            pos_prefill = jnp.maximum(
                jnp.arange(S0b)[None, :] - pad_lens[:, None], 0
            ).astype(jnp.int32)
            logits, caches = fwd(state, ids, caches, jnp.int32(0), amask, pos_prefill)
            valid = jnp.arange(S0b)[None, :] >= pad_lens[:, None]
            seen = _prompt_seen_mask(ids, valid, logits.shape[-1]) if use_seen else None
            key, sk = jax.random.split(key)
            nxt = sample(logits[:, -1], sk, seen, 0)  # last real token: col S0b-1
            seen = _mark_seen(seen, nxt)
            done = (nxt == eos_token_id) if eos_token_id is not None else jnp.zeros((B,), bool)

            def step(carry, xs):
                k_i, t = xs
                if use_seen:
                    caches, tok, done, seen = carry
                else:
                    (caches, tok, done), seen = carry, None
                pos = jnp.int32(S0b) + t
                pos_ids = (pos - pad_lens)[:, None].astype(jnp.int32)
                lg, caches = fwd(state, tok[:, None], caches, pos, amask, pos_ids)
                n = sample(lg[:, -1], k_i, seen, t + 1)
                n = jnp.where(done, jnp.int32(pad_token_id), n)
                new_done = done | (n == eos_token_id) if eos_token_id is not None else done
                out = (caches, n, new_done)
                return (out + (_mark_seen(seen, n),) if use_seen else out), n

            if max_new > 1:
                keys = jax.random.split(key, max_new - 1)
                init = (caches, nxt, done) + ((seen,) if use_seen else ())
                _, rest = jax.lax.scan(step, init, (keys, jnp.arange(max_new - 1)))
                return jnp.concatenate([nxt[:, None], rest.T], axis=1)
            return nxt[:, None]

        return run

    def generate_speculative(self, input_ids, draft_model, max_new_tokens=32,
                             gamma=4, eos_token_id=None, pad_token_id=None):
        """Speculative greedy decoding (reference ecosystem: PaddleNLP
        speculative/draft-model decoding; Leviathan et al.): the small
        draft model proposes `gamma` tokens autoregressively, the target
        verifies them in ONE forward, the longest agreeing prefix is
        accepted plus the target's own next token. Greedy acceptance makes
        the output EXACTLY the target's greedy continuation — the draft
        only changes how many target forwards it takes.

        One jitted program: a lax.while_loop over draft-propose /
        target-verify rounds on fixed-shape caches; per-round cache
        positions are dynamic scalars (stale KV beyond the accepted point
        is masked by the position mask until overwritten by the next
        round's writes). Returns [B, S0 + max_new_tokens] ids.
        """
        ids = to_tensor(input_ids)._data.astype(jnp.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        S0b = prompt_bucket(S0)
        key = ("spec", B, S0b, max_new_tokens, gamma, eos_token_id, pad_token_id,
               id(draft_model))
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(key)
        if run is None:
            run = cache[key] = _compilemem.ledgered_jit(
                self._build_speculative_fn(
                    draft_model, B, S0b, max_new_tokens, gamma,
                    eos_token_id, pad_token_id),
                key=f"generate.speculative[B{B},S{S0b},n{max_new_tokens},"
                    f"g{gamma}]")
            _compilemem.ledger.note_cache_size("generate", len(cache))
        ids_p = jnp.pad(ids, ((0, 0), (0, S0b - S0)), constant_values=pad_token_id)
        gen = run(self.raw_state_dict(), draft_model.raw_state_dict(),
                  ids_p, jnp.int32(S0))
        return Tensor(jnp.concatenate([ids, gen], axis=1), stop_gradient=True)

    def _build_speculative_fn(self, draft_model, B, S0b, max_new, gamma,
                              eos_token_id, pad_token_id):
        model = self
        total = S0b + max_new + gamma + 1  # cache headroom for one overshoot

        fwd = _cache_fwd

        def run(t_state, d_state, ids, true_len):
            t_caches = model.init_cache(B, total)
            d_caches = draft_model.init_cache(B, total)
            # prefill both on the padded prompt
            t_logits, t_caches = fwd(model, t_state, ids, t_caches, jnp.int32(0))
            _, d_caches = fwd(draft_model, d_state, ids, d_caches, jnp.int32(0))
            last = jax.lax.dynamic_index_in_dim(t_logits, true_len - 1, 1, False)
            first = jnp.argmax(last.astype(jnp.float32), -1).astype(jnp.int32)  # [B]

            out = jnp.full((B, max_new + gamma + 1), jnp.int32(pad_token_id))
            out = out.at[:, 0].set(first)
            done = (first == eos_token_id) if eos_token_id is not None else jnp.zeros((B,), bool)

            # carry: n_gen = tokens generated so far (incl. their kv NOT yet
            # written beyond position true_len + n_gen - 1)
            def cond(c):
                t_caches, d_caches, out, n_gen, done = c
                return (n_gen < max_new) & ~jnp.all(done)

            def body(c):
                t_caches, d_caches, out, n_gen, done = c
                pos = true_len + n_gen - 1  # cache position of out[:, n_gen-1]
                # --- draft proposes gamma tokens from out[:, n_gen-1]
                cur = jax.lax.dynamic_index_in_dim(out, n_gen - 1, 1, False)

                def draft_step(carry, i):
                    d_caches, tok = carry
                    lg, d_caches = fwd(draft_model, d_state, tok[:, None],
                                       d_caches, pos + i)
                    nxt = jnp.argmax(lg[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
                    return (d_caches, nxt), nxt

                (d_caches, _), proposals = jax.lax.scan(
                    draft_step, (d_caches, cur), jnp.arange(gamma))
                proposals = proposals.T  # [B, gamma]

                # --- target verifies: one forward over [cur, proposals[:-1]]
                # ... i.e. gamma tokens starting at cache position pos
                block = jnp.concatenate([cur[:, None], proposals[:, :-1]], 1)
                t_lg, t_caches = fwd(model, t_state, block, t_caches, pos)
                t_choice = jnp.argmax(t_lg.astype(jnp.float32), -1).astype(jnp.int32)  # [B, gamma]
                # accept while target agrees with the draft proposal
                agree = t_choice[:, :-1] == proposals[:, :-1] if gamma > 1 else \
                    jnp.ones((B, 0), bool)
                n_acc = jnp.concatenate(
                    [jnp.ones((B, 1), bool), agree], 1).cumprod(1).sum(1).astype(jnp.int32)
                # accepted tokens: proposals[:, :n_acc-1] then target's pick
                # at the first disagreement — uniformly: token i (0-based)
                # of this round is proposals[:, i] while i < n_acc-1, and
                # t_choice[:, n_acc-1] at i == n_acc-1
                idx = jnp.arange(gamma)[None, :]
                round_toks = jnp.where(idx < (n_acc - 1)[:, None], proposals,
                                       jnp.take_along_axis(t_choice, (n_acc - 1)[:, None], 1))
                # done rows emit pad forever
                round_toks = jnp.where(done[:, None], jnp.int32(pad_token_id), round_toks)
                if eos_token_id is not None:
                    hit = (round_toks == eos_token_id) & (idx < n_acc[:, None])
                    # truncate acceptance at the first eos
                    eos_pos = jnp.where(hit.any(1), hit.argmax(1).astype(jnp.int32),
                                        jnp.int32(gamma))
                    n_acc = jnp.minimum(n_acc, eos_pos + 1)
                    done = done | hit.any(1)
                # a row emits pad beyond its OWN acceptance: a row that hit
                # eos this round must not leak the model's post-eos
                # continuation when the batch advances past its n_acc
                round_toks = jnp.where(idx < n_acc[:, None], round_toks,
                                       jnp.int32(pad_token_id))
                # rows finish at different n_acc: advance by the BATCH MIN so
                # every row's cache stays in lockstep (simple + correct;
                # throughput loss only when rows diverge)
                step_n = jnp.maximum(jnp.min(jnp.where(done, jnp.int32(gamma), n_acc)), 1)
                out = jax.lax.dynamic_update_slice(
                    out, jnp.where(idx < step_n, round_toks,
                                   jax.lax.dynamic_slice(out, (0, n_gen), (B, gamma))),
                    (0, n_gen))
                return (t_caches, d_caches, out, n_gen + step_n, done)

            t_caches, d_caches, out, n_gen, done = jax.lax.while_loop(
                cond, body, (t_caches, d_caches, out, jnp.int32(1), done))
            return out[:, :max_new]

        return run

    def _generate_beam(self, input_ids, max_new_tokens, num_beams, length_penalty,
                       eos_token_id, pad_token_id):
        ids = to_tensor(input_ids)._data.astype(jnp.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        S0b = prompt_bucket(S0)
        key = ("beam", B, S0b, max_new_tokens, num_beams, float(length_penalty),
               eos_token_id, pad_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(key)
        if run is None:
            run = cache[key] = _compilemem.ledgered_jit(
                self._build_beam_fn(B, S0b, max_new_tokens, num_beams,
                                    length_penalty, eos_token_id, pad_token_id),
                key=f"generate.beam[B{B},S{S0b},n{max_new_tokens},"
                    f"w{num_beams}]",
            )
            _compilemem.ledger.note_cache_size("generate", len(cache))
        ids_p = jnp.pad(ids, ((0, 0), (0, S0b - S0)), constant_values=pad_token_id)
        gen = run(self.raw_state_dict(), ids_p, jnp.int32(S0))
        return Tensor(jnp.concatenate([ids, gen], axis=1), stop_gradient=True)

    def _build_beam_fn(self, B, S0b, max_new, K, length_penalty, eos_token_id,
                       pad_token_id):
        """Static-width beam search in one compiled program: prefill once on
        [B], replicate the caches to [B*K] beam rows, then a lax.scan where
        every step scores [B, K*V], takes the top-K joint (score, token)
        pairs, and GATHERS the beam-reordered caches (jnp.take along the
        row axis — the XLA equivalent of the reference's beam reorder on
        cache tensors). Finished beams (emitted eos) are frozen: only their
        pad continuation keeps the score, so they compete unchanged."""
        model = self
        total = S0b + max_new
        NEG = jnp.float32(-1e9)

        def fwd(state, toks, caches, pos):
            return _cache_fwd(model, state, toks, caches, pos)

        def lp_norm(length):
            if not length_penalty:
                return jnp.float32(1.0)
            return ((5.0 + length.astype(jnp.float32)) / 6.0) ** length_penalty

        def run(state, ids, true_len):
            caches = model.init_cache(B, total)
            logits, caches = fwd(state, ids, caches, jnp.int32(0))
            last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                                keepdims=False)  # [B, V]
            logp = jax.nn.log_softmax(last.astype(jnp.float32), axis=-1)
            V = logp.shape[-1]
            scores0, tok0 = jax.lax.top_k(logp, K)  # [B, K]
            # beam rows: [B*K, ...] (beam-major within batch)
            caches = tuple(
                (jnp.repeat(kc, K, axis=0), jnp.repeat(vc, K, axis=0))
                for kc, vc in caches
            )
            toks = jnp.full((B, K, max_new), jnp.int32(pad_token_id))
            toks = toks.at[:, :, 0].set(tok0)
            done = (tok0 == eos_token_id) if eos_token_id is not None else jnp.zeros((B, K), bool)

            def step(carry, t):
                caches, toks, scores, done, pos = carry
                cur = jax.lax.dynamic_index_in_dim(toks, jnp.maximum(t - 1, 0), 2,
                                                   keepdims=False)  # [B, K]
                lg, new_caches = fwd(state, cur.reshape(B * K, 1), caches, pos)
                logp = jax.nn.log_softmax(lg[:, -1].astype(jnp.float32), -1).reshape(B, K, V)
                # finished beams: only pad continues, at zero cost
                pad_only = jnp.full((V,), NEG).at[pad_token_id].set(0.0)
                logp = jnp.where(done[:, :, None], pad_only[None, None], logp)
                joint = scores[:, :, None] + logp  # [B, K, V]
                top_s, top_i = jax.lax.top_k(joint.reshape(B, K * V), K)  # [B, K]
                src_beam = top_i // V
                new_tok = (top_i % V).astype(jnp.int32)
                flat_src = (jnp.arange(B)[:, None] * K + src_beam).reshape(-1)
                new_caches = tuple(
                    (jnp.take(kc, flat_src, axis=0), jnp.take(vc, flat_src, axis=0))
                    for kc, vc in new_caches
                )
                toks = jnp.take_along_axis(toks, src_beam[:, :, None], axis=1)
                toks = jax.lax.dynamic_update_index_in_dim(
                    jnp.moveaxis(toks, 2, 0), new_tok, t, 0
                )
                toks = jnp.moveaxis(toks, 0, 2)
                done = jnp.take_along_axis(done, src_beam, axis=1)
                if eos_token_id is not None:
                    done = done | (new_tok == eos_token_id)
                return (new_caches, toks, top_s, done, pos + 1), None

            if max_new > 1:
                (caches, toks, scores, done, _), _ = jax.lax.scan(
                    step, (caches, toks, scores0, done, true_len), jnp.arange(1, max_new)
                )
            else:
                scores = scores0
            lengths = jnp.where(done, jnp.argmax(toks == eos_token_id, axis=2) + 1,
                                max_new) if eos_token_id is not None else jnp.full((B, K), max_new)
            final = scores / lp_norm(lengths)
            best = jnp.argmax(final, axis=1)  # [B]
            return jnp.take_along_axis(toks, best[:, None, None], axis=1)[:, 0]

        return run

    def _build_generate_fn(self, B, S0b, max_new, do_sample, temperature, top_k,
                           top_p, repetition_penalty, min_length,
                           eos_token_id, pad_token_id):
        """Compiled for the (B, S0b bucket, max_new) shape; the true prompt
        length is a dynamic scalar: prefill runs on the right-padded bucket,
        the first token samples from logits[true_len-1], and decode starts
        writing the cache at true_len (pad K/V beyond it are never visible —
        the causal position mask excludes columns > current position).
        Returns the [B, max_new] generated tokens (prompt re-attached
        outside the compiled program)."""
        model = self
        total = S0b + max_new

        def fwd(state, toks, caches, pos):
            return _cache_fwd(model, state, toks, caches, pos)

        sample = _make_sampler(do_sample, temperature, top_k, top_p,
                               repetition_penalty, min_length, eos_token_id)
        use_seen = repetition_penalty != 1.0  # static: no carry cost otherwise

        def run(state, ids, true_len, key):
            caches = model.init_cache(B, total)
            logits, caches = fwd(state, ids, caches, jnp.int32(0))
            last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                                keepdims=False)
            # seen-token mask over the true prompt (padding excluded)
            valid = jnp.arange(S0b)[None, :] < true_len
            seen = _prompt_seen_mask(ids, valid, logits.shape[-1]) if use_seen else None
            key, sk = jax.random.split(key)
            nxt = sample(last, sk, seen, 0)
            seen = _mark_seen(seen, nxt)
            done = jnp.zeros((B,), bool)
            if eos_token_id is not None:
                done = nxt == eos_token_id

            def step(carry, xs):
                k_i, i = xs
                if use_seen:
                    caches, tok, pos, done, seen = carry
                else:
                    (caches, tok, pos, done), seen = carry, None
                lg, caches = fwd(state, tok[:, None], caches, pos)
                n = sample(lg[:, -1], k_i, seen, i)
                n = jnp.where(done, jnp.int32(pad_token_id), n)
                new_done = done | (n == eos_token_id) if eos_token_id is not None else done
                out = (caches, n, pos + 1, new_done)
                return (out + (_mark_seen(seen, n),) if use_seen else out), n

            if max_new > 1:
                keys = jax.random.split(key, max_new - 1)
                init = (caches, nxt, true_len, done) + ((seen,) if use_seen else ())
                _, rest = jax.lax.scan(step, init, (keys, jnp.arange(1, max_new)))
                return jnp.concatenate([nxt[:, None], rest.T], axis=1)
            return nxt[:, None]

        return run
