"""Autoregressive generation (reference: PaddleNLP GenerationMixin.generate +
paddle/fluid/inference decode loop; TPU-native: ONE jitted program — prefill
fills a fixed-shape KV cache via dynamic_update_slice, the decode loop is a
lax.scan (static trip count, static shapes — XLA requirements), greedy or
temperature sampling via jax.random.categorical).

The cache never reallocates: [B, S0 + max_new_tokens, kv_heads, head_dim]
per layer, written at the running position. PAPERS.md ragged-paged-attention
is the multi-tenant serving upgrade path.
"""
import jax
import jax.numpy as jnp

from .framework.core import Tensor, to_tensor


class GenerationMixin:
    """Mixin for causal LMs whose forward supports
    (input_ids, past_key_values, cache_position, use_cache) -> (logits, caches).
    """

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        if dtype is None:
            dtype = self.lm_head.weight.dtype if getattr(self, "lm_head", None) is not None \
                else self.llama.embed_tokens.weight.dtype
        import numpy as np

        jdt = dtype if not isinstance(dtype, str) else jnp.dtype(dtype)
        shape = (batch_size, max_length, cfg.num_key_value_heads, cfg.head_dim)
        return tuple(
            (jnp.zeros(shape, jdt), jnp.zeros(shape, jdt))
            for _ in range(cfg.num_hidden_layers)
        )

    def generate(self, input_ids, max_new_tokens=32, do_sample=False, temperature=1.0,
                 top_k=0, eos_token_id=None, pad_token_id=None, seed=0):
        """Returns [B, S0 + max_new_tokens] int32 token ids (prompt included).
        After eos, a sequence keeps emitting pad_token_id (defaults to eos)."""
        ids = to_tensor(input_ids)._data.astype(jnp.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        cache_key = (B, S0, max_new_tokens, do_sample, float(temperature), int(top_k),
                     eos_token_id, pad_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(cache_key)
        if run is None:
            run = cache[cache_key] = jax.jit(
                self._build_generate_fn(B, S0, max_new_tokens, do_sample, temperature,
                                        top_k, eos_token_id, pad_token_id)
            )
        state = self.raw_state_dict()
        out = run(state, ids, jax.random.PRNGKey(seed))
        return Tensor(out, stop_gradient=True)

    def _build_generate_fn(self, B, S0, max_new, do_sample, temperature, top_k,
                           eos_token_id, pad_token_id):
        model = self
        total = S0 + max_new

        def fwd(state, toks, caches, pos):
            overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
            wrapped = [(Tensor(kc), Tensor(vc)) for kc, vc in caches]
            logits, presents = model.functional_call(
                overrides, Tensor(toks), past_key_values=wrapped,
                cache_position=Tensor(pos), use_cache=True, training=False,
            )
            return logits._data, tuple((p[0]._data, p[1]._data) for p in presents)

        def sample(logits, key):
            logits = logits.astype(jnp.float32)
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / jnp.maximum(temperature, 1e-6)
            if top_k and top_k > 0:
                kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

        def run(state, ids, key):
            caches = model.init_cache(B, total)
            logits, caches = fwd(state, ids, caches, jnp.int32(0))
            key, sk = jax.random.split(key)
            nxt = sample(logits[:, -1], sk)
            done = jnp.zeros((B,), bool)
            if eos_token_id is not None:
                done = nxt == eos_token_id

            def step(carry, k_i):
                caches, tok, pos, done = carry
                lg, caches = fwd(state, tok[:, None], caches, pos)
                n = sample(lg[:, -1], k_i)
                n = jnp.where(done, jnp.int32(pad_token_id), n)
                new_done = done | (n == eos_token_id) if eos_token_id is not None else done
                return (caches, n, pos + 1, new_done), n

            if max_new > 1:
                keys = jax.random.split(key, max_new - 1)
                (_, _, _, _), rest = jax.lax.scan(
                    step, (caches, nxt, jnp.int32(S0), done), keys
                )
                return jnp.concatenate([ids, nxt[:, None], rest.T], axis=1)
            return jnp.concatenate([ids, nxt[:, None]], axis=1)

        return run
