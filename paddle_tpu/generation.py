"""Autoregressive generation (reference: PaddleNLP GenerationMixin.generate +
paddle/fluid/inference decode loop; TPU-native: ONE jitted program — prefill
fills a fixed-shape KV cache via dynamic_update_slice, the decode loop is a
lax.scan (static trip count, static shapes — XLA requirements), greedy or
temperature sampling via jax.random.categorical).

The cache never reallocates: [B, S0b + max_new_tokens, kv_heads, head_dim]
per layer, written at the running position. Prompt lengths are BUCKETED to
powers of two (min 16): the compiled program is keyed on the bucket, takes
the true length as a dynamic scalar, and right-pads the prompt — so serving
compiles O(log S) variants, not one per prompt length. PAPERS.md
ragged-paged-attention is the multi-tenant serving upgrade path.
"""
import jax
import jax.numpy as jnp

from .framework.core import Tensor, to_tensor

_MIN_BUCKET = 16


def prompt_bucket(s0):
    """Smallest power-of-two bucket >= s0 (floor _MIN_BUCKET)."""
    b = _MIN_BUCKET
    while b < s0:
        b *= 2
    return b


class GenerationMixin:
    """Mixin for causal LMs whose forward supports
    (input_ids, past_key_values, cache_position, use_cache) -> (logits, caches).
    """

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        if dtype is None:
            if getattr(self, "lm_head", None) is not None:
                dtype = self.lm_head.weight.dtype
            else:
                # model-agnostic probe: cache in the compute dtype of the
                # first parameter (llama tied-embed, GPT wte, ...)
                dtype = next(iter(self.parameters())).dtype
        import numpy as np

        jdt = dtype if not isinstance(dtype, str) else jnp.dtype(dtype)
        shape = (batch_size, max_length, cfg.num_key_value_heads, cfg.head_dim)
        return tuple(
            (jnp.zeros(shape, jdt), jnp.zeros(shape, jdt))
            for _ in range(cfg.num_hidden_layers)
        )

    def generate(self, input_ids, max_new_tokens=32, do_sample=False, temperature=1.0,
                 top_k=0, eos_token_id=None, pad_token_id=None, seed=0):
        """Returns [B, S0 + max_new_tokens] int32 token ids (prompt included).
        After eos, a sequence keeps emitting pad_token_id (defaults to eos)."""
        ids = to_tensor(input_ids)._data.astype(jnp.int32)
        B, S0 = ids.shape
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        S0b = prompt_bucket(S0)
        cache_key = (B, S0b, max_new_tokens, do_sample, float(temperature), int(top_k),
                     eos_token_id, pad_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        run = cache.get(cache_key)
        if run is None:
            run = cache[cache_key] = jax.jit(
                self._build_generate_fn(B, S0b, max_new_tokens, do_sample, temperature,
                                        top_k, eos_token_id, pad_token_id)
            )
        ids_p = jnp.pad(ids, ((0, 0), (0, S0b - S0)), constant_values=pad_token_id)
        state = self.raw_state_dict()
        gen = run(state, ids_p, jnp.int32(S0), jax.random.PRNGKey(seed))
        return Tensor(jnp.concatenate([ids, gen], axis=1), stop_gradient=True)

    def _build_generate_fn(self, B, S0b, max_new, do_sample, temperature, top_k,
                           eos_token_id, pad_token_id):
        """Compiled for the (B, S0b bucket, max_new) shape; the true prompt
        length is a dynamic scalar: prefill runs on the right-padded bucket,
        the first token samples from logits[true_len-1], and decode starts
        writing the cache at true_len (pad K/V beyond it are never visible —
        the causal position mask excludes columns > current position).
        Returns the [B, max_new] generated tokens (prompt re-attached
        outside the compiled program)."""
        model = self
        total = S0b + max_new

        def fwd(state, toks, caches, pos):
            overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
            wrapped = [(Tensor(kc), Tensor(vc)) for kc, vc in caches]
            logits, presents = model.functional_call(
                overrides, Tensor(toks), past_key_values=wrapped,
                cache_position=Tensor(pos), use_cache=True, training=False,
            )
            return logits._data, tuple((p[0]._data, p[1]._data) for p in presents)

        def sample(logits, key):
            logits = logits.astype(jnp.float32)
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / jnp.maximum(temperature, 1e-6)
            if top_k and top_k > 0:
                kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

        def run(state, ids, true_len, key):
            caches = model.init_cache(B, total)
            logits, caches = fwd(state, ids, caches, jnp.int32(0))
            last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                                keepdims=False)
            key, sk = jax.random.split(key)
            nxt = sample(last, sk)
            done = jnp.zeros((B,), bool)
            if eos_token_id is not None:
                done = nxt == eos_token_id

            def step(carry, k_i):
                caches, tok, pos, done = carry
                lg, caches = fwd(state, tok[:, None], caches, pos)
                n = sample(lg[:, -1], k_i)
                n = jnp.where(done, jnp.int32(pad_token_id), n)
                new_done = done | (n == eos_token_id) if eos_token_id is not None else done
                return (caches, n, pos + 1, new_done), n

            if max_new > 1:
                keys = jax.random.split(key, max_new - 1)
                (_, _, _, _), rest = jax.lax.scan(
                    step, (caches, nxt, true_len, done), keys
                )
                return jnp.concatenate([nxt[:, None], rest.T], axis=1)
            return nxt[:, None]

        return run
