"""Static lock model shared by the concurrency rules (ISSUE 10 tentpole).

Builds, from the ModuleIndex:

* a **lock table** — every ``threading.Lock/RLock/Condition`` (and
  project lock wrappers like ``_StampedRLock``) bound to a module-level
  name or a ``self.<attr>``, identified at CLASS granularity:
  ``pkg.mod.NAME`` or ``pkg.mod.Class.attr``. Two instances of the same
  class's lock attribute are the same *order class* — exactly what lock-
  ordering discipline ranks.
* a light **call graph** — calls resolvable statically: same-module
  functions, ``self.method``, attributes whose class was inferred from
  ``self.x = Cls(...)`` in ``__init__``, imported names, plus a
  unique-method-name fallback for everything else.
* per-function **acquire summaries** — the fixpoint closure of "locks
  this function may take", so a ``with self._locked_dispatch(...)`` body
  counts as holding whatever that contextmanager takes around its yield.

The walkers (:func:`walk_held`) then replay each function with a held-lock
stack, which is all the concurrency rules need: lock-order edges, calls
made under a lock, writes made outside one.
"""
import ast

from ..index import dotted

__all__ = ["LockModel", "build", "walk_held"]

#: constructor names that mint a lock-like object. Semaphores excluded on
#: purpose: they are counting gates, not mutual-exclusion order members.
LOCK_CTORS = {"Lock", "RLock", "Condition", "_StampedRLock", "StampedRLock"}

#: method names too generic for the unique-method call-resolution
#: fallback — resolving `x.get(...)` to some random class would poison
#: the call graph with false edges
_COMMON_METHODS = {
    "get", "put", "set", "pop", "add", "clear", "wait", "join", "start",
    "stop", "close", "run", "append", "extend", "items", "values", "keys",
    "update", "copy", "read", "write", "send", "recv", "acquire",
    "release", "step", "reset", "result", "next", "submit", "open",
    "load", "save", "name", "info", "warning", "error", "debug", "beat",
    "register", "observe", "inc", "dec", "report", "snapshot", "flush",
}


def _contains_lock_ctor(expr):
    """True if any node in ``expr`` calls a lock constructor — covers
    ``self.lock = lock or threading.RLock()`` style defaults."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name in LOCK_CTORS:
                return True
    return False


class LockModel:
    def __init__(self, index):
        self.index = index
        self.module_locks = {}   # module -> {name: lock_id}
        self.class_locks = {}    # (module, cls) -> {attr: lock_id}
        self.attr_types = {}     # (module, cls) -> {attr: (module2, cls2)}
        self.method_owners = {}  # method name -> [(module, cls)]
        self.acquires = {}       # (module, qualname) -> {lock_id: lineno}
        self._build_tables()
        self._build_acquire_summaries()

    # ---- lock + type tables ----------------------------------------------
    def _build_tables(self):
        for fi in self.index.iter_files(("paddle_tpu/", "scripts/",
                                         "tests/")):
            mod = fi.module
            for node in fi.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and _contains_lock_ctor(node.value):
                    self.module_locks.setdefault(mod, {})[
                        node.targets[0].id] = f"{mod}.{node.targets[0].id}"
            for cls_name, cls in fi.classes.items():
                key = (mod, cls_name)
                for fn in ast.walk(cls):
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    self.method_owners.setdefault(fn.name, []).append(key)
                    for node in ast.walk(fn):
                        if not (isinstance(node, ast.Assign)
                                and len(node.targets) == 1):
                            continue
                        tgt = node.targets[0]
                        if not (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            continue
                        if _contains_lock_ctor(node.value):
                            self.class_locks.setdefault(key, {})[tgt.attr] \
                                = f"{mod}.{cls_name}.{tgt.attr}"
                        t = self._infer_ctor_class(fi, node.value)
                        if t is not None:
                            self.attr_types.setdefault(key, {})[tgt.attr] = t

    def _infer_ctor_class(self, fi, expr):
        """``self.x = Cls(...)`` (possibly behind ``arg or Cls(...)``) ->
        the (module, class) of Cls when it resolves inside the index."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            head, _, tail = name.rpartition(".")
            # bare class name in this module / imported
            if not head:
                if name in fi.classes:
                    return (fi.module, name)
                target = fi.import_aliases.get(name)
                if target and "." in target:
                    m, _, c = target.rpartition(".")
                    ofi = self.index.by_module.get(m)
                    if ofi is not None and c in ofi.classes:
                        return (m, c)
            else:
                target = fi.import_aliases.get(head, head)
                ofi = self.index.by_module.get(target)
                if ofi is not None and tail in ofi.classes:
                    return (target, tail)
        return None

    # ---- name -> lock resolution -----------------------------------------
    def lock_for_expr(self, fi, cls_name, expr):
        """Resolve a with-item (or attribute receiver) expression to a
        lock id, or None. Handles bare names (module lock, imported module
        lock), ``self.attr``, ``mod.NAME``, and — for receivers like
        ``entry.handle._cond`` — a unique-attr fallback: an attribute name
        that is a lock attr of exactly ONE class in the index resolves to
        that class's lock."""
        if isinstance(expr, ast.Name):
            mod_locks = self.module_locks.get(fi.module, {})
            if expr.id in mod_locks:
                return mod_locks[expr.id]
            target = fi.import_aliases.get(expr.id)
            if target and "." in target:
                m, _, n = target.rpartition(".")
                return self.module_locks.get(m, {}).get(n)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and cls_name is not None:
                hit = self.class_locks.get((fi.module, cls_name),
                                           {}).get(expr.attr)
                if hit is not None:
                    return hit
            name = dotted(expr)
            if name is not None and "." in name:
                head, _, tail = name.rpartition(".")
                target = fi.import_aliases.get(head, head)
                hit = self.module_locks.get(target, {}).get(tail)
                if hit is not None:
                    return hit
            # unique lock-attr fallback (rep._cond, handle._cond, ...)
            owners = [(k, v[expr.attr]) for k, v in self.class_locks.items()
                      if expr.attr in v]
            if len(owners) == 1:
                return owners[0][1]
        return None

    # ---- call resolution --------------------------------------------------
    def resolve_call(self, fi, cls_name, call):
        """Best-effort static callee: ``(module, qualname)`` or None."""
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in fi.functions:
                return (fi.module, f.id)
            target = fi.import_aliases.get(f.id)
            if target and "." in target:
                m, _, n = target.rpartition(".")
                ofi = self.index.by_module.get(m)
                if ofi is not None and n in ofi.functions:
                    return (m, n)
            return None
        if not isinstance(f, ast.Attribute):
            return None
        # self.method()
        if isinstance(f.value, ast.Name) and f.value.id == "self" \
                and cls_name is not None:
            q = f"{cls_name}.{f.attr}"
            if q in fi.functions:
                return (fi.module, q)
            # self.<typed attr>.method()
        # self.x.method() with inferred attr type
        if isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id == "self" and cls_name is not None:
            t = self.attr_types.get((fi.module, cls_name),
                                    {}).get(f.value.attr)
            if t is not None:
                m, c = t
                ofi = self.index.by_module.get(m)
                if ofi is not None and f"{c}.{f.attr}" in ofi.functions:
                    return (m, f"{c}.{f.attr}")
        # module.func() / imported alias
        name = dotted(f)
        if name is not None and "." in name:
            head, _, tail = name.rpartition(".")
            target = fi.import_aliases.get(head, head)
            ofi = self.index.by_module.get(target)
            if ofi is not None and tail in ofi.functions:
                return (target, tail)
        # unique-method fallback
        if f.attr not in _COMMON_METHODS and not f.attr.startswith("__"):
            owners = self.method_owners.get(f.attr, [])
            if len(owners) == 1:
                m, c = owners[0]
                return (m, f"{c}.{f.attr}")
        return None

    # ---- acquire summaries (fixpoint) ------------------------------------
    def _direct_acquires(self, fi, qualname, fn):
        cls_name = qualname.split(".")[0] if "." in qualname else None
        out = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                lid = self.lock_for_expr(fi, cls_name, item.context_expr)
                if lid is not None:
                    out.setdefault(lid, item.context_expr.lineno)
        return out

    def _build_acquire_summaries(self):
        direct, calls = {}, {}
        for fi in self.index.iter_files(("paddle_tpu/", "scripts/",
                                         "tests/")):
            for qualname, fn in fi.functions.items():
                key = (fi.module, qualname)
                cls_name = qualname.split(".")[0] if "." in qualname \
                    else None
                direct[key] = self._direct_acquires(fi, qualname, fn)
                out = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        tgt = self.resolve_call(fi, cls_name, node)
                        if tgt is not None and tgt != key:
                            out.add(tgt)
                calls[key] = out
        self.acquires = {k: dict(v) for k, v in direct.items()}
        # fixpoint: propagate callee acquires up (bounded by lattice height)
        for _ in range(len(self.acquires)):
            changed = False
            for key, callees in calls.items():
                acq = self.acquires[key]
                for c in callees:
                    for lid, line in self.acquires.get(c, {}).items():
                        if lid not in acq:
                            acq[lid] = line
                            changed = True
            if not changed:
                break

    def yield_holds(self, key):
        """Locks a generator contextmanager holds AROUND ITS YIELD — the
        set its caller's with-body runs under. Direct with-nesting only:
        transient acquisitions before/after the yield are edges of the
        cm function itself, not holds of the caller. Empty for
        non-generators."""
        cached = getattr(self, "_yield_holds", None)
        if cached is None:
            cached = self._yield_holds = {}
        if key in cached:
            return cached[key]
        out = cached[key] = set()
        fi = self.index.by_module.get(key[0])
        fn = fi.functions.get(key[1]) if fi is not None else None
        if fn is not None:
            cls_name = key[1].split(".")[0] if "." in key[1] else None

            def go(node, held):
                if isinstance(node, ast.With):
                    inner = list(held)
                    for item in node.items:
                        lid = self.lock_for_expr(fi, cls_name,
                                                 item.context_expr)
                        if lid is not None and lid not in inner:
                            inner.append(lid)
                    for stmt in node.body:
                        go(stmt, inner)
                    return
                if isinstance(node, ast.Yield):
                    out.update(held)
                for child in ast.iter_child_nodes(node):
                    if not isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                        go(child, held)

            for stmt in fn.body:
                go(stmt, [])
        return out

    def with_item_locks(self, fi, cls_name, item):
        """Locks a with-item holds over its body: the item itself if it
        IS a lock, or — when it calls a contextmanager function — the
        locks that cm holds around its yield (``with
        self._locked_dispatch(...):`` holds the compile + dispatch
        locks)."""
        lid = self.lock_for_expr(fi, cls_name, item.context_expr)
        if lid is not None:
            return [lid]
        if isinstance(item.context_expr, ast.Call):
            tgt = self.resolve_call(fi, cls_name, item.context_expr)
            if tgt is not None:
                return sorted(self.yield_holds(tgt))
        return []


def walk_held(model, fi, qualname, fn, visit):
    """Replay ``fn`` with a held-lock stack.

    ``visit(node, held)`` is called for every statement/expression node in
    source order with the tuple of lock ids held at that point. Nested
    function defs and lambdas are walked with an EMPTY held stack (they
    run later, on their own thread/stack)."""
    cls_name = qualname.split(".")[0] if "." in qualname else None

    def go(node, held):
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                visit(item.context_expr, tuple(inner))
                for lid in model.with_item_locks(fi, cls_name, item):
                    if lid not in inner:
                        inner.append(lid)
            for stmt in node.body:
                go(stmt, tuple(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                go(stmt, ())
            return
        visit(node, held)
        for child in ast.iter_child_nodes(node):
            go(child, held)

    for stmt in fn.body:
        go(stmt, ())
