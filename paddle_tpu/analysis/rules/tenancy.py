"""Tenant-label boundedness rule (ISSUE 19 satellite).

* ``tenant-label-bounded`` — every ``tenant=`` metric label in
  ``paddle_tpu/`` is populated from a DECLARED tenant's ``.name``
  attribute (or a string literal), never from a request-supplied
  variable. The tenant plane's whole label-cardinality contract rests
  on one code shape: ``Tenant.__init__`` validates the name and the
  registry bounds how many exist, so ``{"tenant": <something>.name}``
  is bounded by construction — while ``{"tenant": user_string}`` mints
  a new time series per attacker-chosen value until the metrics
  registry is the outage. The rule pins the shape at the ``labels=`` /
  ``gauge_labels=`` call sites, where the leak would actually happen.
"""
import ast

from ..engine import Finding, rule

#: keyword arguments that feed metric label dicts
_LABEL_KWARGS = ("labels", "gauge_labels")


def _bounded(value):
    """True when the label value is bounded by construction: a string
    literal, or an ``<expr>.name`` attribute read (the declared-Tenant
    shape — ``Tenant.__init__`` validated it, the registry bounded it)."""
    if isinstance(value, ast.Constant) and isinstance(value.value, str):
        return True
    return isinstance(value, ast.Attribute) and value.attr == "name"


@rule("tenant-label-bounded",
      description='a {"tenant": ...} metric label is populated from a '
                  "declared Tenant's .name (or a literal), never a "
                  "request-supplied variable")
def tenant_label_bounded(index):
    findings = []
    for fi in index.iter_files("paddle_tpu/"):
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg not in _LABEL_KWARGS \
                        or not isinstance(kw.value, ast.Dict):
                    continue
                for key, value in zip(kw.value.keys, kw.value.values):
                    if not (isinstance(key, ast.Constant)
                            and key.value == "tenant"):
                        continue
                    if _bounded(value):
                        continue
                    findings.append(Finding(
                        fi.path, value.lineno, "tenant-label-bounded",
                        f'{kw.arg}={{"tenant": '
                        f"{ast.unparse(value)}}} — label values must be a "
                        f"declared Tenant's .name (bounded by the "
                        f"registry) or a literal; a request-supplied "
                        f"string mints unbounded metric series"))
    return findings
