"""``devprof-seam`` — timed-dispatch device syncs only inside the devprof
sampling seam (ISSUE 17).

``observability/devprof.py`` owns the process's timed-dispatch
``block_until_ready`` sync: the sampling cadence guarantees at most one
blocking wait per window and the measured wall lands in the per-program
device-time table. A raw ``block_until_ready`` anywhere else in the
package is an unattributed, unbounded stall — it serializes the dispatch
pipeline (exactly what the async decode path exists to avoid), is
invisible to /perfz, and the ``hostsync`` rule only guards the traced
callables and the decode-critical methods, not the whole tree.

Deliberate exceptions carry ``# lint: devprof-seam-ok`` (e.g. the
user-facing ``Tensor.block_until_ready`` wait API in ``distributed/``,
or the device warm-probe).
"""
import ast

from ..engine import Finding, rule

#: the sampling seam itself — the one blessed timed-sync site
ALLOWED = "paddle_tpu/observability/devprof.py"


@rule("devprof-seam",
      markers=("devprof-seam-ok",),
      description="block_until_ready timed-dispatch syncs only inside "
                  "observability/devprof.py's sampling seam")
def devprof_seam(index):
    findings = []
    for fi in index.iter_files("paddle_tpu/"):
        if fi.path == ALLOWED:
            continue
        for node in ast.walk(fi.tree):
            if (not isinstance(node, ast.Attribute)
                    or node.attr != "block_until_ready"):
                continue
            findings.append(Finding(
                fi.path, node.lineno, "devprof-seam",
                "raw block_until_ready outside the devprof sampling seam "
                "is an unattributed pipeline stall — route timed syncs "
                "through observability.devprof (or justify with "
                "# lint: devprof-seam-ok)"))
    return findings
