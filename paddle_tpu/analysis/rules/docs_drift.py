"""``metric-doc-drift`` — port of the ISSUE 7 doc-drift lint.

Every metric/span name LITERAL registered in ``paddle_tpu/`` must appear
in a ``docs/OBSERVABILITY.md`` table first cell, and every non-wildcard
documented name must still be registered — dashboards and scrapers can
trust the doc tables. Dynamic names (f-strings) are documented with
``<...>`` placeholders, which match as wildcards forward and are exempt
from the reverse check.
"""
import re

from ..engine import Finding, rule

#: registration call names whose string first argument is a metric/span
#: name: metrics registry, thread spans, request-trace, frontend families
REG_ATTRS = {"counter", "gauge", "histogram", "bump",
             "span",
             "child", "event", "begin", "span_at",
             "_class_hist"}

_NAME = re.compile(r"[a-z][a-z0-9_.<>*]*\Z")

DOC = "docs/OBSERVABILITY.md"


def _doc_names(text):
    names, patterns = set(), []
    for line in text.splitlines():
        if not line.startswith("|"):
            continue
        first = line.split("|")[1]
        for tok in re.findall(r"`([^`]+)`", first):
            if not _NAME.match(tok):
                continue
            if "<" in tok or "*" in tok:
                part = re.sub(r"<[^>]+>", "WILDCARDMARK", tok)
                pat = (re.escape(part)
                       .replace("WILDCARDMARK", "[A-Za-z0-9_.]+")
                       .replace(re.escape("*"), "[A-Za-z0-9_.]+"))
                patterns.append(re.compile(pat + r"\Z"))
            else:
                names.add(tok)
    return names, patterns


@rule("metric-doc-drift",
      description="registered metric/span names and the "
                  "docs/OBSERVABILITY.md tables must agree both ways")
def metric_doc_drift(index):
    registered = index.string_call_args(REG_ATTRS, prefix=("paddle_tpu/",))
    doc = index.doc(DOC)
    if doc is None:
        return [Finding(DOC, 0, "metric-doc-drift",
                        "docs/OBSERVABILITY.md is missing")]
    doc_names, doc_patterns = _doc_names(doc)
    findings = []
    for name in sorted(registered):
        if name in doc_names or any(p.match(name) for p in doc_patterns):
            continue
        path, line = sorted(registered[name])[0]
        findings.append(Finding(
            path, line, "metric-doc-drift",
            f"registered name {name!r} is missing from the "
            f"docs/OBSERVABILITY.md tables — add a row"))
    for name in sorted(doc_names):
        if name not in registered:
            findings.append(Finding(
                DOC, 0, "metric-doc-drift",
                f"documented name {name!r} is not registered anywhere in "
                f"paddle_tpu/ — remove the row or fix the name"))
    return findings
