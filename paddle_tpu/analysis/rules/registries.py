"""Registry-drift rules (ISSUE 10 tentpole part d).

* ``env-registry`` — every ``PADDLE_*`` environment read in
  ``paddle_tpu/`` goes through the ``utils/envs.py`` helpers (one place
  to parse, default, and armor against garbage values), and every name
  the helpers are called with appears in the generated ``docs/ENVS.md``
  table — both directions, so the operator-facing doc can be trusted.
  Writes (``os.environ[...] = ...`` — the launcher exporting contract
  vars to children) are not reads and stay legal.
* ``chaos-site-registry`` — every chaos site string armed in tests
  (``plan.fail("ckpt.write")`` ...) exists at an injection seam
  (``chaos.site("ckpt.write")``) somewhere — a typo'd site silently
  injects NOTHING and the test passes vacuously; and every production
  seam is referenced from tests or docs, so dead seams surface.

``--write-envs-doc`` regenerates docs/ENVS.md from the same harvest,
preserving hand-written description cells by variable name.
"""
import ast
import re

from ..engine import Finding, rule
from ..index import dotted

ENV_HELPERS = {"env_int", "env_float", "env_bool", "env_str"}
ENVS_DOC = "docs/ENVS.md"
_ENVS_FILE = "paddle_tpu/utils/envs.py"

#: os.environ mutation methods that are not reads
_ENV_WRITES = {"setdefault", "pop", "update", "clear"}


def _env_reads(index):
    """Raw PADDLE_* env reads in paddle_tpu/ outside utils/envs.py:
    [(path, line, rendered-expr)]."""
    out = []
    for fi in index.iter_files("paddle_tpu/"):
        if fi.path == _ENVS_FILE:
            continue
        for node in ast.walk(fi.tree):
            # os.environ.get("PADDLE_X") / os.getenv("PADDLE_X")
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in ("os.environ.get", "os.getenv") and node.args:
                    val = fi.resolve_str(node.args[0], index=index)
                    if val is not None and val.startswith("PADDLE_"):
                        out.append((fi.path, node.lineno,
                                    f"{name}({val!r})"))
            # os.environ["PADDLE_X"] in Load context
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and dotted(node.value) == "os.environ":
                val = fi.resolve_str(node.slice, index=index)
                if val is not None and val.startswith("PADDLE_"):
                    out.append((fi.path, node.lineno,
                                f"os.environ[{val!r}]"))
    return out


def harvest_env_names(index):
    """Every PADDLE_* name handed to an envs.py helper:
    {name: {"helper": str, "default": str|None, "readers": [paths]}}."""
    out = {}
    for fi in index.iter_files(("paddle_tpu/", "scripts/")):
        for node in ast.walk(fi.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            f = node.func
            helper = (f.attr if isinstance(f, ast.Attribute)
                      else f.id if isinstance(f, ast.Name) else None)
            if helper is None:
                continue
            helper = helper.lstrip("_")
            if helper not in ENV_HELPERS:
                continue
            name = fi.resolve_str(node.args[0], index=index)
            if name is None or not name.startswith("PADDLE_"):
                continue
            default = None
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                default = repr(node.args[1].value)
            rec = out.setdefault(name, {"helper": helper,
                                        "default": default,
                                        "readers": set()})
            rec["readers"].add(fi.path)
            if rec["default"] is None:
                rec["default"] = default
    return out


def _doc_env_rows(text):
    """{name: description} from the ENVS.md table."""
    rows = {}
    for line in text.splitlines():
        if not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.split("|")[1:-1]]
        if len(cells) < 2:
            continue
        m = re.match(r"`(PADDLE_[A-Z0-9_]+)`", cells[0])
        if m:
            rows[m.group(1)] = cells[-1]
    return rows


@rule("env-registry",
      description="PADDLE_* reads go through utils/envs.py and appear in "
                  "the generated docs/ENVS.md table")
def env_registry(index):
    findings = [
        Finding(path, line, "env-registry",
                f"raw {expr} — read it through the paddle_tpu.utils.envs "
                f"helpers (env_int/env_float/env_bool/env_str)")
        for path, line, expr in _env_reads(index)
    ]
    registered = harvest_env_names(index)
    doc = index.doc(ENVS_DOC)
    if doc is None:
        findings.append(Finding(
            ENVS_DOC, 0, "env-registry",
            "docs/ENVS.md is missing — generate it with "
            "`python -m paddle_tpu.analysis --write-envs-doc`"))
        return findings
    doc_rows = _doc_env_rows(doc)
    for name in sorted(registered):
        if name not in doc_rows:
            path = sorted(registered[name]["readers"])[0]
            findings.append(Finding(
                path, 0, "env-registry",
                f"{name} is read but undocumented — regenerate the table "
                f"with `python -m paddle_tpu.analysis --write-envs-doc` "
                f"and fill in its description"))
    for name in sorted(doc_rows):
        if name not in registered:
            findings.append(Finding(
                ENVS_DOC, 0, "env-registry",
                f"documented env var {name} is not read through the envs "
                f"helpers anywhere — remove the row or fix the name"))
    return findings


def render_envs_doc(index, previous=None):
    """The full docs/ENVS.md text, preserving descriptions from
    ``previous`` (the current doc text) by variable name."""
    registered = harvest_env_names(index)
    old = _doc_env_rows(previous) if previous else {}
    lines = [
        "# Environment variables",
        "",
        "Generated by `python -m paddle_tpu.analysis --write-envs-doc` "
        "from every",
        "`utils/envs.py` helper call in the tree; the `env-registry` "
        "analysis rule",
        "fails CI when this table and the code drift (either direction). "
        "Edit the",
        "Description cells freely — regeneration preserves them by "
        "variable name.",
        "",
        "| Variable | Parsed as | Default | Read by | Description |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(registered):
        rec = registered[name]
        readers = sorted(rec["readers"])
        shown = ", ".join(f"`{r}`" for r in readers[:2])
        if len(readers) > 2:
            shown += f" +{len(readers) - 2}"
        desc = old.get(name, "") or "(fill me in)"
        lines.append(
            f"| `{name}` | {rec['helper'][4:]} | "
            f"{rec['default'] if rec['default'] is not None else '—'} | "
            f"{shown} | {desc} |")
    lines.append("")
    return "\n".join(lines)


# ---- chaos sites ----------------------------------------------------------

#: FaultPlan arming methods whose first argument names a site
_ARM_METHODS = {"fail", "exit", "truncate", "delay", "on_site"}


@rule("chaos-site-registry",
      description="chaos sites armed in tests exist at injection seams, "
                  "and every production seam is referenced in tests/docs")
def chaos_site_registry(index):
    seams = index.string_call_args({"site"},
                                   prefix=("paddle_tpu/", "tests/"))
    # AST can't see seams inside triple-quoted subprocess scripts (the
    # chaos E2E tests ship child programs as strings) — a textual scan
    # catches those; it only ever ADDS seams, never removes
    text_seams = set()
    for fi in index.iter_files(("paddle_tpu/", "tests/")):
        text_seams.update(re.findall(r"chaos\.site\(\s*\"([^\"]+)\"",
                                     fi.source))
    all_seams = set(seams) | text_seams
    armed = index.string_call_args(_ARM_METHODS, prefix=("tests/",))
    findings = []
    for site in sorted(armed):
        if site.endswith("*"):  # FaultRule.matches prefix pattern
            if any(s.startswith(site[:-1]) for s in all_seams):
                continue
        elif site in all_seams:
            continue
        path, line = sorted(armed[site])[0]
        findings.append(Finding(
            path, line, "chaos-site-registry",
            f"chaos site {site!r} is armed here but no chaos.site("
            f"{site!r}) seam exists — the fault injects nothing and the "
            f"test passes vacuously"))
    # reverse: every production seam is exercised or documented somewhere.
    # A test arming a trailing-* pattern (FaultRule.matches semantics)
    # exercises every seam under that prefix — a drill matrix armed as
    # "serving.kv.*" covers each serving.kv.<mode> seam (ISSUE 18).
    wild = [s[:-1] for s in armed if s.endswith("*")]
    test_text = "".join(fi.source for fi in index.iter_files("tests/"))
    doc_text = "\n".join(filter(None, (
        index.doc(f"docs/{n}") for n in
        ("CHAOS.md", "SERVING.md", "CHECKPOINTING.md", "ELASTIC.md",
         "OBSERVABILITY.md", "ANALYSIS.md"))))
    for site in sorted(seams):
        paths = [p for p, _ in seams[site]]
        if not any(p.startswith("paddle_tpu/") for p in paths):
            continue  # test-local synthetic seams need no catalogue entry
        if site in test_text or f"`{site}`" in doc_text \
                or any(site.startswith(w) for w in wild):
            continue
        path, line = sorted(seams[site])[0]
        findings.append(Finding(
            path, line, "chaos-site-registry",
            f"chaos seam {site!r} is neither exercised by any test nor "
            f"documented — add it to the docs/CHAOS.md catalogue (or a "
            f"test that arms it)"))
    return findings
