"""Rule plugins. Importing this package registers every rule with the
engine registry (``paddle_tpu.analysis.engine.RULES``) — a new rule module
just needs an import line here and a ``@rule(...)`` decorator there."""
from . import (  # noqa: F401  (imported for registration side effects)
    checkpoint,
    devprof_seam,
    docs_drift,
    hostsync,
    ledger,
    locks,
    profiler_capture,
    registries,
    tenancy,
    timing,
)
