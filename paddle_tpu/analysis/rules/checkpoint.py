"""Checkpoint-package invariants — ports of the ISSUE 3/9 lints.

* ``ckpt-atomic-write`` — every byte written into a checkpoint directory
  flows through ``checkpoint/atomic.py`` (temp+fsync+rename); a raw
  write-mode ``open()`` anywhere else in the package is a torn-file bug
  waiting for a preemption.
* ``elastic-membership`` — checkpoint code never derives MEMBERSHIP from
  ``range(world_size)``: after an elastic shrink, a dead rank enumerated
  by range would be waited on (negotiation barriers) or trusted (peer
  candidates) forever. Membership flows through
  ``fleet.elastic.membership.live_ranks``.
"""
import ast
import re

from ..engine import Finding, rule

PKG = "paddle_tpu/distributed/checkpoint/"

#: files OUTSIDE the checkpoint package that carry the same torn-file
#: obligation: a KV-page handoff bundle is adopted by another process's
#: replica mid-request, so its writes need the identical temp+fsync+rename
#: discipline (ISSUE 16); the wire transport (ISSUE 18) carries the same
#: frames, so any file it writes is held to the same rule
ATOMIC_WRITE_PATHS = (PKG, "paddle_tpu/serving/handoff.py",
                      "paddle_tpu/serving/transport.py")

_MODE = re.compile(r"[rwaxbtU+]{1,4}\Z")


def _mode_of(call):
    """The mode string of an open()-style call, or None. Builtin
    ``open(path, mode)`` carries the mode at arg 1; method-style
    ``Path(p).open(mode)`` at arg 0 — accept a mode-shaped string
    constant at either position (the grep this rule replaced matched the
    quoted mode token anywhere in the call)."""
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    for arg in call.args[:2]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and _MODE.match(arg.value):
            return arg.value
    return None


@rule("ckpt-atomic-write",
      markers=("ckpt-atomic-ok",),
      description="checkpoint-directory writes (and handoff bundle "
                  "writes) go through checkpoint/atomic.py "
                  "(temp+fsync+rename)")
def ckpt_atomic_write(index):
    findings = []
    for fi in index.iter_files(ATOMIC_WRITE_PATHS):
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            # any *.open(...) regardless of receiver shape — dotted()
            # would bail on call-chain receivers like Path(p).open("wb"),
            # which the grep this rule replaces used to catch
            f = node.func
            is_open = (isinstance(f, ast.Name) and f.id == "open") or \
                (isinstance(f, ast.Attribute) and f.attr == "open")
            if not is_open:
                continue
            mode = _mode_of(node)
            if mode is None or not any(c in mode for c in "wax+"):
                continue
            findings.append(Finding(
                fi.path, node.lineno, "ckpt-atomic-write",
                f"raw write-mode open(..., {mode!r}) in the checkpoint "
                f"package — all checkpoint-directory writes go through "
                f"checkpoint/atomic.py"))
    return findings


@rule("elastic-membership",
      markers=("elastic-membership-ok",),
      description="checkpoint code derives membership from the negotiated"
                  " live-rank set, never range(world_size)")
def elastic_membership(index):
    findings = []
    for fi in index.iter_files(PKG):
        for node in ast.walk(fi.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "range"):
                continue
            for arg in node.args:
                name = (arg.id if isinstance(arg, ast.Name)
                        else arg.attr if isinstance(arg, ast.Attribute)
                        else None)
                if name == "world_size":
                    findings.append(Finding(
                        fi.path, node.lineno, "elastic-membership",
                        "range(world_size) membership iteration — "
                        "enumerate fleet.elastic.membership.live_ranks() "
                        "(the negotiated live-rank set) instead"))
    return findings
