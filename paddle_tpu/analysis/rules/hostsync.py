"""``host-sync-in-jit`` — the ISSUE 6 decode lint, generalized (ISSUE 10
tentpole part c).

Two protected surfaces:

* **traced function bodies** — any function/lambda handed to
  ``ledgered_jit`` / ``pjit`` (or decorated with them): a host sync inside
  a traced body either fails at trace time in the best case or, worse,
  silently constant-folds a device round-trip into every dispatch.
* **the decode dispatch critical section** — the engine functions the
  double-buffered pipeline keeps host-sync-free so readback hides under
  device compute. The allowlist marker on the designated readback lines is
  ``serve-readback-ok`` (legacy) / ``lint: host-sync-in-jit-ok``.

The forbidden direction is device->host: ``np.asarray`` on device values,
``block_until_ready``, ``device_get``. ``jnp.asarray`` (host->device
upload) never blocks on the device and stays legal.
"""
import ast
import re

from ..engine import Finding, rule

#: engine functions forming the decode dispatch critical section
DECODE_CRITICAL = {
    "paddle_tpu/inference/continuous.py": {
        "step", "_dispatch_decode", "_process_block", "_advance_prefill",
        "drain",
        # disaggregation (ISSUE 16): adopting a handed-off request inserts
        # pages on the decode replica's dispatch path — it must stay as
        # host-sync-free as any other admission (jnp.asarray uploads only;
        # the key_base rebuild is the one designated readback)
        "adopt_request",
        # ragged plane (ISSUE 20): the mixed prefill+decode dispatch IS the
        # decode critical section now — same contract, same designated
        # readbacks (the sync-path host copy and nothing else)
        "_step_ragged", "_dispatch_ragged", "_dispatch_ragged_mixed",
    },
}

#: the traced-shim factories whose callable argument becomes device code
_TRACE_WRAPPERS = {"ledgered_jit", "pjit"}

# (?<!j) spares jnp.asarray; the regex runs per source line for exact
# parity with the original lint (attribute spellings like xs.block_until_
# ready() have no single AST shape)
_SYNC = re.compile(r"(?<!j)np\.asarray\(|block_until_ready|device_get")


def _scan_span(fi, lo, hi, where, findings):
    for ln in range(lo, hi + 1):
        text = fi.line(ln)
        if _SYNC.search(text):
            findings.append(Finding(
                fi.path, ln, "host-sync-in-jit",
                f"blocking host sync inside {where} — move it to a "
                f"designated readback point (or tag a deliberate "
                f"readback with  # lint: host-sync-in-jit-ok)"))


def _traced_callables(fi):
    """(node, description) for every function body that gets traced."""
    out = []
    for node in ast.walk(fi.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name = dec.func if isinstance(dec, ast.Call) else dec
                tail = (name.attr if isinstance(name, ast.Attribute)
                        else name.id if isinstance(name, ast.Name)
                        else None)
                if tail in _TRACE_WRAPPERS:
                    out.append((node, f"traced function {node.name!r}"))
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        tail = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if tail not in _TRACE_WRAPPERS or not node.args:
            continue
        # the traced callable may sit behind vmap/shard_map wrappers:
        # collect every lambda and same-module def referenced in arg0
        for sub in ast.walk(node.args[0]):
            if isinstance(sub, ast.Lambda):
                out.append((sub, "a traced lambda"))
            elif isinstance(sub, ast.Name) and sub.id in fi.functions:
                out.append((fi.functions[sub.id],
                            f"traced function {sub.id!r}"))
    return out


@rule("host-sync-in-jit",
      markers=("serve-readback-ok",),
      description="no device->host sync inside traced functions or the "
                  "decode dispatch critical section")
def host_sync_in_jit(index):
    findings = []
    seen = set()
    for fi in index.iter_files("paddle_tpu/"):
        spans = []
        for node, where in _traced_callables(fi):
            spans.append((node.lineno, node.end_lineno, where))
        for fname in DECODE_CRITICAL.get(fi.path, ()):
            fn = None
            for q, n in fi.functions.items():
                if q == fname or q.endswith(f".{fname}"):
                    fn = n
                    break
            if fn is not None:
                spans.append((fn.lineno, fn.end_lineno,
                              "the decode dispatch critical section"))
        for lo, hi, where in spans:
            key = (fi.path, lo, hi)
            if key in seen:
                continue
            seen.add(key)
            _scan_span(fi, lo, hi, where, findings)
    # the same line can fall in overlapping spans (a traced def inside a
    # critical section) — report once
    uniq = {}
    for f in findings:
        uniq.setdefault((f.path, f.line), f)
    return list(uniq.values())
