"""``profiler-capture`` — every xprof capture goes through the flight
recorder's capture registry (ISSUE 13).

``observability/flightrec.py`` owns the process's ONE on-demand
``jax.profiler`` capture: arming, step counting, the bounded completed-
capture ledger, and the /profilez surface. A raw
``jax.profiler.start_trace`` / ``stop_trace`` anywhere else in the package
is an unledgered, unbounded profile artifact — invisible to /profilez,
able to collide with an armed flight capture, and impossible to correlate
with the anomaly that motivated it. ``profiler.start_xprof_trace`` /
``stop_xprof_trace`` delegate to the registry and stay the public API.

Deliberate exceptions carry ``# lint: profiler-capture-ok``.
"""
import ast

from ..engine import Finding, rule
from ..index import dotted

#: the capture registry itself — the one blessed raw-call site
ALLOWED = "paddle_tpu/observability/flightrec.py"

_CAPTURE_ATTRS = ("start_trace", "stop_trace")


@rule("profiler-capture",
      markers=("profiler-capture-ok",),
      description="raw jax.profiler.start_trace/stop_trace only inside "
                  "observability/flightrec.py's capture registry")
def profiler_capture(index):
    findings = []
    for fi in index.iter_files("paddle_tpu/"):
        if fi.path == ALLOWED:
            continue
        for node in ast.walk(fi.tree):
            if (not isinstance(node, ast.Attribute)
                    or node.attr not in _CAPTURE_ATTRS):
                continue
            base = dotted(node.value)
            if not base or not base.endswith("profiler"):
                continue
            findings.append(Finding(
                fi.path, node.lineno, "profiler-capture",
                f"raw {base}.{node.attr} bypasses the flight recorder's "
                f"capture registry — use observability.flightrec."
                f"arm_capture/start_capture (or justify with "
                f" # lint: profiler-capture-ok)"))
    return findings
