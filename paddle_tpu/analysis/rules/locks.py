"""Concurrency rules (ISSUE 10 tentpole, part b).

These are the rules the bugs PRs 4 and 9 fixed by hand would have hit in
CI: the wedged-dispatcher work rebuilt the serving dispatch locking, and
the shared-root GC race was an unguarded cross-thread mutation. All three
rules read the shared static lock model (:mod:`._lockmodel`).

* ``lock-order`` — build the static acquisition graph over every lock-like
  attribute (``dispatch_lock``, ``_COMPILE_LOCK``, the frontend sweep
  lock, router lock, checkpoint manager locks, ...) and fail on cycles.
  The blessed global order is whatever the acyclic graph says; a new edge
  that closes a cycle is a deadlock waiting for the right interleaving.
* ``blocking-under-lock`` — no ``Event.wait`` / future ``result()`` /
  device sync / ``subprocess`` / store dial inside a ``with <lock>`` body.
  A blocked holder starves every waiter; the serving monitor can even
  declare them dead (PR 4's wedged-dispatcher forensics). ``Condition``
  waits on the HELD condition itself are the designed exception.
* ``shared-mutation-without-lock`` — attributes written from thread entry
  points (``threading.Thread(target=...)`` bodies and what they reach)
  must be written under a lock or be ``_``-prefixed (private = owned by
  one thread by this codebase's convention, e.g. the single-writer
  heartbeat stamps).
"""
import ast

from ..engine import Finding, rule
from ..index import dotted
from . import _lockmodel

_SCOPES = ("paddle_tpu/",)

#: call names that block the calling thread indefinitely (or for a device
#: round-trip) — forbidden while holding a lock
_BLOCKING_ATTRS = {"result", "block_until_ready", "device_get"}
_STORE_CTORS = {"TCPStore"}
#: socket DIALS (ISSUE 18): opening/accepting a connection blocks for a
#: network round-trip (or the connect timeout) — the KV wire transport
#: must never dial under the dispatch or router locks. Post-dial
#: sendall/recv on an already-connected per-request socket is exempt
#: here: the store client's request lock exists to serialize exactly
#: that, and each RPC's socket is private to its call.
_SOCKET_DIALS = {"connect", "accept", "create_connection"}
#: digest validation (ISSUE 18): bundle/blob validation recomputes
#: blake2b chains over megabytes of pages — CPU-bound work no lock
#: holder should do
_DIGEST_ATTRS = {"verify_prompt_digests", "unframe_blob"}


def _model(index):
    # one lock model per index, built lazily and shared by all three rules
    m = getattr(index, "_lockmodel", None)
    if m is None:
        m = index._lockmodel = _lockmodel.LockModel(index)
    return m


@rule("lock-order",
      description="static lock-acquisition graph over threading locks "
                  "must be acyclic (a cycle is a deadlock schedule)")
def lock_order(index):
    model = _model(index)
    edges = {}  # (src, dst) -> (path, line)

    for fi in index.iter_files(_SCOPES):
        for qualname, fn in fi.functions.items():
            cls_name = qualname.split(".")[0] if "." in qualname else None

            def visit(node, held, fi=fi, cls_name=cls_name):
                if not held:
                    return
                acquired = ()
                if isinstance(node, ast.Call):
                    tgt = model.resolve_call(fi, cls_name, node)
                    if tgt is not None:
                        acquired = model.acquires.get(tgt, {})
                else:
                    lid = model.lock_for_expr(fi, cls_name, node) \
                        if isinstance(node, (ast.Name, ast.Attribute)) \
                        else None
                    # with-item expressions arrive here via walk_held's
                    # pre-visit; a bare attribute read is not an acquire
                    acquired = {lid: node.lineno} if lid is not None \
                        and getattr(node, "_pt_with_item", False) else ()
                for dst in acquired:
                    for src in held:
                        if src != dst and (src, dst) not in edges:
                            edges[(src, dst)] = (fi.path, node.lineno)

            # mark with-items so visit() can tell an acquire from a read
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        item.context_expr._pt_with_item = True
            _lockmodel.walk_held(model, fi, qualname, fn, visit)

    # cycle detection: DFS over the edge graph
    graph = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)
    findings, seen_cycles = [], set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cycle = path[path.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                # a `# lint: lock-order-ok` on ANY edge of the cycle
                # suppresses it — the justification belongs on whichever
                # acquisition the author deems the deliberate one (the
                # engine's line-anchored suppression also applies, to the
                # first edge's line)
                if any("lint: lock-order-ok" in
                       index.files[edges[(a, b)][0]].line(edges[(a, b)][1])
                       for a, b in zip(cycle, cycle[1:])
                       if edges[(a, b)][0] in index.files):
                    continue
                edge_sites = [
                    f"{a} -> {b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                    for a, b in zip(cycle, cycle[1:])]
                path0, line0 = edges[(cycle[0], cycle[1])]
                findings.append(Finding(
                    path0, line0, "lock-order",
                    "lock acquisition cycle: " + "; ".join(edge_sites) +
                    " — pick one global order and restructure the "
                    "inverted acquisition"))
            elif nxt not in visited:
                on_path.add(nxt)
                dfs(nxt, path + [nxt], on_path)
                on_path.discard(nxt)
        visited.add(node)

    visited = set()
    for start in sorted(graph):
        if start not in visited:
            dfs(start, [start], {start})
    return findings


@rule("blocking-under-lock",
      markers=("serve-readback-ok",),
      description="no Event.wait/result()/device sync/subprocess/store "
                  "dial while holding a lock")
def blocking_under_lock(index):
    model = _model(index)
    findings = []

    for fi in index.iter_files(_SCOPES):
        for qualname, fn in fi.functions.items():
            cls_name = qualname.split(".")[0] if "." in qualname else None

            def visit(node, held, fi=fi, cls_name=cls_name):
                if not held or not isinstance(node, ast.Call):
                    return
                name = dotted(node.func)
                hit = None
                if name in ("time.sleep",):
                    hit = "time.sleep"
                elif name is not None and (name.startswith("subprocess.")
                                           or name.endswith(".Popen")
                                           or name == "Popen"):
                    hit = "subprocess"
                elif name is not None and \
                        name.split(".")[-1] in _STORE_CTORS:
                    hit = "store dial"
                elif name == "np.asarray":
                    hit = "device sync (np.asarray)"
                elif name is not None and \
                        name.split(".")[-1] in ("create_connection",
                                                "unframe_blob"):
                    hit = f"{name.split('.')[-1]}"
                elif isinstance(node.func, ast.Attribute):
                    a = node.func.attr
                    if a in _SOCKET_DIALS:
                        hit = f"socket dial (.{a}())"
                    elif a in _DIGEST_ATTRS:
                        hit = f"digest validation (.{a}())"
                    elif a == "from_bytes" and "Bundle" in (name or ""):
                        hit = "bundle digest validation (.from_bytes())"
                    elif a in ("wait", "wait_for"):
                        # Condition.wait on the HELD lock is the designed
                        # pattern; waiting on anything else while holding
                        # a lock starves the lock's other users
                        rec = model.lock_for_expr(fi, cls_name,
                                                  node.func.value)
                        if rec is None or rec not in held:
                            hit = f".{a}() on a non-held object"
                    elif a in _BLOCKING_ATTRS:
                        hit = f".{a}()"
                if hit is not None:
                    findings.append(Finding(
                        fi.path, node.lineno, "blocking-under-lock",
                        f"{hit} while holding {', '.join(held)} — move "
                        f"the blocking call outside the lock (or justify "
                        f"with  # lint: blocking-under-lock-ok)"))

            _lockmodel.walk_held(model, fi, qualname, fn, visit)
    return findings


def _thread_entry_points(index, model):
    """(module, qualname) of every function handed to
    ``threading.Thread(target=...)``, resolved statically — including
    nested defs (resolved within the enclosing function's scope)."""
    entries = set()
    for fi in index.iter_files(_SCOPES):
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname is None or fname.split(".")[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                tgt = kw.value
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    for cls_name, cls in fi.classes.items():
                        q = f"{cls_name}.{tgt.attr}"
                        if q in fi.functions and any(
                                n is node for n in ast.walk(cls)):
                            entries.add((fi.module, q))
                elif isinstance(tgt, ast.Name):
                    if tgt.id in fi.functions:
                        entries.add((fi.module, tgt.id))
                    else:
                        # nested def in the enclosing function: walk it
                        # directly under its own (nested) qualname
                        for q, fn in fi.functions.items():
                            for sub in ast.walk(fn):
                                if isinstance(sub, ast.FunctionDef) \
                                        and sub.name == tgt.id and any(
                                            n is node
                                            for n in ast.walk(fn)):
                                    entries.add((fi.module,
                                                 f"{q}.<{tgt.id}>"))
    return entries


@rule("shared-mutation-without-lock",
      description="thread entry points must lock-guard writes to shared "
                  "(public) attributes, or mark them _-private "
                  "single-writer fields")
def shared_mutation(index):
    model = _model(index)
    entries = _thread_entry_points(index, model)

    # transitively reachable statically-resolvable callees of each entry,
    # plus — per callee — the locks held at EVERY resolvable call site: a
    # helper only ever invoked under its owner's lock (chaos
    # FaultRule._should_fire under FaultPlan._lock) starts its walk with
    # that lock held instead of being flagged for its caller's discipline
    reach = set(entries)
    frontier = list(entries)
    call_map = {}
    callsite_held = {}
    for fi in index.iter_files(_SCOPES):
        for qualname, fn in fi.functions.items():
            cls_name = qualname.split(".")[0] if "." in qualname else None
            outs = set()

            def note_call(node, held, fi=fi, cls_name=cls_name,
                          outs=outs):
                if isinstance(node, ast.Call):
                    tgt = model.resolve_call(fi, cls_name, node)
                    if tgt is not None:
                        outs.add(tgt)
                        callsite_held.setdefault(tgt, []).append(
                            frozenset(held))

            _lockmodel.walk_held(model, fi, qualname, fn, note_call)
            call_map[(fi.module, qualname)] = outs
    while frontier:
        key = frontier.pop()
        base = key[1].split(".<")[0]  # nested entries reach via enclosing
        for tgt in call_map.get((key[0], base), ()):
            if tgt not in reach:
                reach.add(tgt)
                frontier.append(tgt)

    findings = []
    for (mod, qualname) in sorted(reach):
        fi = index.by_module.get(mod)
        if fi is None:
            continue
        base, _, nested = qualname.partition(".<")
        fn = fi.functions.get(base)
        if fn is None:
            continue
        if nested:  # resolve the nested def node
            want = nested.rstrip(">")
            fn = next((n for n in ast.walk(fn)
                       if isinstance(n, ast.FunctionDef)
                       and n.name == want), None)
            if fn is None:
                continue
        # locks provably held at every resolvable call site of this
        # function (empty for the entry points themselves)
        always_held = frozenset()
        if (mod, qualname) not in entries:
            sites = callsite_held.get((mod, base), [])
            if sites:
                always_held = frozenset.intersection(*sites)

        def visit(node, held, fi=fi, always_held=always_held):
            if held or always_held \
                    or not isinstance(node, (ast.Assign, ast.AugAssign)):
                return
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if not isinstance(tgt, ast.Attribute) \
                        or tgt.attr.startswith("_"):
                    continue
                base_name = dotted(tgt.value)
                if base_name is None:
                    continue
                parts = base_name.split(".")
                # only `self.<public chain>` is a SHARED write: parameter
                # objects are request-scoped single-owner handoffs, and a
                # _-prefixed holder (self._local.x — thread-locals, owned
                # sub-objects) marks the container private to one thread
                if parts[0] != "self" \
                        or any(p.startswith("_") for p in parts[1:]):
                    continue
                findings.append(Finding(
                    fi.path, tgt.lineno, "shared-mutation-without-lock",
                    f"write to shared attribute "
                    f"{base_name}.{tgt.attr} from a thread entry "
                    f"path without holding a lock — guard it, or "
                    f"_-prefix it if it is single-writer"))

        _lockmodel.walk_held(model, fi, qualname if not nested else base,
                             fn, visit)
    return findings
