"""Hot-path hygiene rules — ports of the ISSUE 2/4 ci.sh grep lints.

AST-based where the greps were textual, so comments and docstrings no
longer false-positive and string-embedded ``print(`` stops mattering.
"""
import ast

from ..engine import Finding, rule
from ..index import dotted

#: files on the training/serving hot path: timing belongs in
#: paddle_tpu.observability (spans + registry metrics), diagnostics in
#: structured telemetry — never raw wall-clock reads or prints
HOT_PATHS = (
    "paddle_tpu/jit_api.py",
    "paddle_tpu/distributed/train_step.py",
    "paddle_tpu/inference/continuous.py",
    "paddle_tpu/io/dataloader.py",
    "paddle_tpu/distributed/communication/ops.py",
    "paddle_tpu/serving/frontend.py",
    "paddle_tpu/serving/scheduler.py",
    "paddle_tpu/serving/router.py",
)


@rule("hot-path-timing",
      description="no raw time.time()/print() in hot-path files — route "
                  "timing/diagnostics through paddle_tpu.observability")
def hot_path_timing(index):
    findings = []
    for path in HOT_PATHS:
        fi = index.files.get(path)
        if fi is None:
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name == "time.time":
                findings.append(Finding(
                    fi.path, node.lineno, "hot-path-timing",
                    "raw time.time() on a hot path — use time.monotonic/"
                    "perf_counter feeding the observability registry"))
            elif name == "print":
                findings.append(Finding(
                    fi.path, node.lineno, "hot-path-timing",
                    "print() on a hot path — route diagnostics through "
                    "paddle_tpu.observability"))
    return findings


@rule("serving-sleep",
      description="no blocking time.sleep anywhere in the serving control "
                  "plane — dispatchers wait on their wake event, and the "
                  "supervisor's decision loop (serving/supervisor.py, "
                  "ISSUE 12) waits on its cadence event; a sleeping "
                  "control loop can neither be woken by a death nor "
                  "stopped promptly")
def serving_sleep(index):
    findings = []
    for fi in index.iter_files("paddle_tpu/serving/"):
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call) \
                    and dotted(node.func) == "time.sleep":
                findings.append(Finding(
                    fi.path, node.lineno, "serving-sleep",
                    "time.sleep holds a dispatcher/supervisor loop hostage "
                    "for the full duration — wait on the wake/cadence "
                    "event (threading.Event.wait) instead"))
    return findings
