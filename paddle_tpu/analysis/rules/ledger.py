"""``compile-ledger`` — port of the ISSUE 8 completeness lint.

Every XLA compile site in ``paddle_tpu/`` must flow through
``observability/compilemem.py`` (``ledgered_jit`` for jit sites,
``record_compile`` brackets for AOT export sites) so the compile ledger —
/compilez, churn detection, OOM forensics — is complete by CONSTRUCTION.
A raw ``jax.jit`` reference or a ``.lower(...).compile()`` chain anywhere
else is a ledger blind spot.
"""
import ast

from ..engine import Finding, rule


@rule("compile-ledger",
      markers=("compile-ledger-ok",),
      description="every compile site goes through compilemem.ledgered_jit"
                  " / record_compile")
def compile_ledger(index):
    findings = []
    for fi in index.iter_files("paddle_tpu/"):
        for node in ast.walk(fi.tree):
            hit = None
            # any `jax.jit` reference (call, partial, decorator)
            if (isinstance(node, ast.Attribute) and node.attr == "jit"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                hit = "raw jax.jit"
            # <expr>.lower(...).compile(...) AOT chains
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "compile"
                  and isinstance(node.func.value, ast.Call)
                  and isinstance(node.func.value.func, ast.Attribute)
                  and node.func.value.func.attr == "lower"):
                hit = ".lower(...).compile()"
            if hit is not None:
                findings.append(Finding(
                    fi.path, node.lineno, "compile-ledger",
                    f"{hit} bypasses the compile ledger — use "
                    f"observability.compilemem.ledgered_jit / "
                    f"record_compile (or tag a deliberate exception with "
                    f" # compile-ledger-ok)"))
    return findings
