"""paddle_tpu.analysis — the project-native static-analysis engine
(ISSUE 10).

One shared parse (``index.ModuleIndex``), a rule-plugin registry
(``engine.RULES``), findings as ``path:line: RULE_ID message`` with inline
``# lint: <rule-id>-ok`` markers and a checked-in baseline file
(``scripts/analysis_baseline.txt``), and a CLI::

    python -m paddle_tpu.analysis --ci        # full tree, exit 1 on findings
    python -m paddle_tpu.analysis --changed   # findings on touched lines only
    python -m paddle_tpu.analysis --list      # rule catalogue

The subpackage itself is dependency-free (ast + stdlib only) — the cost
of ``python -m paddle_tpu.analysis`` is the parent package import plus
ONE parse of the tree shared by every rule, which is what lets ci.sh
replace five separate parse-the-world heredoc processes with a single
invocation. See docs/ANALYSIS.md for the rule catalogue and suppression
semantics.
"""
from . import rules  # noqa: F401  — registers every rule
from .engine import RULES, Finding, run_rules  # noqa: F401
from .index import ModuleIndex  # noqa: F401

__all__ = ["RULES", "Finding", "ModuleIndex", "run_rules", "main"]


def main(argv=None):
    from .cli import main as _main

    return _main(argv)
