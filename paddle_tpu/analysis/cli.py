"""CLI for the analysis engine (``python -m paddle_tpu.analysis``)."""
import argparse
import os
import subprocess
import sys

from .engine import (DEFAULT_BASELINE, RULES, baseline_key,
                     format_finding, load_baseline, run_rules)
from .index import ModuleIndex
from .rules import registries


def _changed_lines(root, base):
    """{path: set(linenos)} of working-tree lines added/modified vs the
    merge-base with ``base`` (the --changed mode: incremental PRs are
    judged on touched lines only, not the whole-file baseline)."""
    def git(*args):
        return subprocess.run(
            ["git", *args], cwd=root, check=True, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL).stdout

    mb = None
    for candidate in ([base] if base else ["origin/main", "origin/master",
                                           "main", "master"]):
        try:
            mb = git("merge-base", "HEAD", candidate).strip()
            break
        except subprocess.CalledProcessError:
            continue
    if mb is None:
        mb = "HEAD"
    out = {}
    path = None
    for line in git("diff", "-U0", mb, "--", "*.py").splitlines():
        if line.startswith("+++ b/"):
            path = line[6:]
        elif line.startswith("@@") and path is not None:
            # @@ -a,b +c,d @@ — the +c,d span is the new-side lines
            new = line.split("+")[1].split(" ")[0]
            start, _, count = new.partition(",")
            start, count = int(start), int(count or 1)
            out.setdefault(path, set()).update(
                range(start, start + count))
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="project-native static analysis (docs/ANALYSIS.md)")
    p.add_argument("--ci", action="store_true",
                   help="run every rule over the whole tree (the ci.sh "
                        "lint phase); exit 1 on findings")
    p.add_argument("--changed", action="store_true",
                   help="only report findings on lines changed vs the "
                        "git merge-base (incremental PR mode)")
    p.add_argument("--base", default=None,
                   help="merge-base ref for --changed (default: "
                        "origin/main, falling back to main)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids (default: all)")
    p.add_argument("--root", default=None,
                   help="repo root to analyze (default: the checkout "
                        "this package was imported from)")
    p.add_argument("--list", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore scripts/analysis_baseline.txt")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current finding into the baseline "
                        "file")
    p.add_argument("--write-envs-doc", action="store_true",
                   help="regenerate docs/ENVS.md (preserves description "
                        "cells) and exit")
    args = p.parse_args(argv)

    if args.list:
        for rid, spec in RULES.items():
            print(f"{rid:32s} {spec.description}")
        return 0

    rule_ids = args.rules.split(",") if args.rules else None
    if rule_ids:
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            p.error(f"unknown rule(s) {unknown}; --list shows the "
                    f"catalogue")
    index = ModuleIndex(root=args.root)
    for path, err in index.errors:
        print(f"{path}:0: parse-error {err}", file=sys.stderr)

    if args.write_envs_doc:
        doc_path = os.path.join(index.root, registries.ENVS_DOC)
        previous = index.doc(registries.ENVS_DOC)
        text = registries.render_envs_doc(index, previous=previous)
        with open(doc_path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {registries.ENVS_DOC}")
        return 0

    if args.write_baseline:
        # the accepted-debt set must be computed from scratch: filtering
        # through the EXISTING baseline (or --changed) here would rewrite
        # the file without the already-accepted entries, resurrecting
        # them as hard failures on the next --ci run
        findings, _, _ = run_rules(index, rule_ids, baseline=None,
                                   changed_lines=None)
        path = os.path.join(index.root, DEFAULT_BASELINE)
        with open(path, "w", encoding="utf-8") as f:
            f.write("# Accepted analysis debt — one rule|path|line-text "
                    "key per line.\n# Regenerate: python -m "
                    "paddle_tpu.analysis --write-baseline\n")
            for fnd in findings:
                f.write(baseline_key(index, fnd) + "\n")
        print(f"wrote {len(findings)} entries to {DEFAULT_BASELINE}")
        return 0

    baseline = None if args.no_baseline else load_baseline(index.root)
    changed = _changed_lines(index.root, args.base) if args.changed \
        else None
    findings, n_marked, n_base = run_rules(
        index, rule_ids, baseline=baseline, changed_lines=changed)

    for fnd in findings:
        print(format_finding(fnd))
    n_rules = len(rule_ids) if rule_ids else len(RULES)
    status = "FAIL" if findings or index.errors else "ok"
    print(f"analysis: {n_rules} rules over {len(index.files)} files — "
          f"{len(findings)} findings ({n_marked} marker-suppressed, "
          f"{n_base} baselined) [{status}]",
          file=sys.stderr)
    return 1 if findings or index.errors else 0
