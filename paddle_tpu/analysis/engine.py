"""Rule registry, finding model, suppression + baseline semantics.

A *rule* is a function ``(ModuleIndex) -> [Finding]`` registered under a
stable id. The engine runs every requested rule over ONE shared index and
then applies the two suppression layers:

* **inline markers** — a finding whose source line carries
  ``lint: <rule-id>-ok`` (or one of the rule's declared legacy marker
  aliases, e.g. ``serve-readback-ok``) is dropped. Markers are the
  reviewed, justified-in-place escape hatch.
* **baseline file** — ``scripts/analysis_baseline.txt`` holds findings
  that predate a rule and are accepted as debt. Entries are keyed by
  ``rule|path|stripped-line-text`` (not line numbers, which drift); a
  baselined finding is reported only with ``--no-baseline``. The shipped
  tree keeps this file EMPTY — new debt needs a reviewed inline marker.

See docs/ANALYSIS.md for the rule catalogue and how to add a rule.
"""
import os
from collections import namedtuple

__all__ = ["Finding", "RuleSpec", "RULES", "rule", "run_rules",
           "load_baseline", "baseline_key", "DEFAULT_BASELINE"]

DEFAULT_BASELINE = "scripts/analysis_baseline.txt"

Finding = namedtuple("Finding", "path line rule message")


def format_finding(f):
    return f"{f.path}:{f.line}: {f.rule} {f.message}"


RuleSpec = namedtuple("RuleSpec", "rule_id fn markers description")

#: rule_id -> RuleSpec, in registration order (rules/__init__ imports the
#: rule modules, so importing paddle_tpu.analysis.rules populates this)
RULES = {}


def rule(rule_id, markers=(), description=""):
    """Register ``fn(index) -> [Finding]`` as a rule.

    ``markers`` are legacy inline tokens that suppress this rule in
    addition to the canonical ``lint: <rule-id>-ok`` — they keep the
    pre-ISSUE-10 in-tree annotations (``serve-readback-ok`` etc.) working
    unchanged."""
    def deco(fn):
        RULES[rule_id] = RuleSpec(rule_id, fn, tuple(markers), description)
        return fn
    return deco


def _suppressed(index, finding, spec):
    fi = index.files.get(finding.path)
    if fi is None:
        return False
    text = fi.line(finding.line)
    if f"lint: {spec.rule_id}-ok" in text:
        return True
    return any(tok in text for tok in spec.markers)


def baseline_key(index, finding):
    fi = index.files.get(finding.path)
    text = fi.line(finding.line).strip() if fi else ""
    return f"{finding.rule}|{finding.path}|{text}"


def load_baseline(root, path=DEFAULT_BASELINE):
    """The accepted-debt set: one ``rule|path|line-text`` key per line,
    ``#`` comments and blanks ignored. Missing file = empty baseline."""
    entries = set()
    try:
        with open(os.path.join(root, path), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    entries.add(line)
    except OSError:
        pass
    return entries


def run_rules(index, rule_ids=None, baseline=None, changed_lines=None):
    """Run ``rule_ids`` (default: every registered rule) over ``index``.

    Returns ``(findings, suppressed_count, baselined_count)`` with marker-
    and baseline-suppressed findings removed. ``changed_lines`` (the
    ``--changed`` mode): ``{path: set(linenos)}`` — findings outside it are
    dropped, EXCEPT whole-tree registry findings reported at line 0
    (doc-drift style rules), which always apply to the files they name.
    """
    if rule_ids is None:
        rule_ids = list(RULES)
    findings, n_marked, n_base = [], 0, 0
    for rid in rule_ids:
        spec = RULES[rid]
        for f in spec.fn(index):
            if _suppressed(index, f, spec):
                n_marked += 1
                continue
            if baseline and baseline_key(index, f) in baseline:
                n_base += 1
                continue
            if changed_lines is not None and f.line > 0:
                if f.line not in changed_lines.get(f.path, ()):
                    continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, n_marked, n_base
