"""One shared parse of the tree for every rule (ISSUE 10 tentpole).

The five pre-ISSUE-10 lints each re-walked and re-parsed the repo inside a
``scripts/ci.sh`` heredoc; the analysis engine parses every file exactly
once into a :class:`ModuleIndex` — AST + per-module symbol table + import
graph — and every registered rule reads from it. Rules therefore cost one
AST walk each, not one filesystem walk each, and the whole lint phase is a
single ``python -m paddle_tpu.analysis --ci`` process.

The index is deliberately plain data: rules should stay small functions
over it. Anything two rules both need (dotted-name rendering, module-level
string constants, import alias resolution) belongs here, not copied into
rule modules.
"""
import ast
import os

__all__ = ["FileInfo", "ModuleIndex", "dotted"]

#: directories never worth indexing (generated/vendored/VCS)
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules",
              "telemetry", "xprof_traces"}


def dotted(node):
    """Render a Name/Attribute chain as ``"a.b.c"``; None for anything
    else (calls, subscripts) anywhere in the chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FileInfo:
    """One parsed module: source, AST, and the symbol facts rules share."""

    __slots__ = ("path", "module", "source", "lines", "tree", "is_package",
                 "import_aliases", "str_constants", "functions", "classes")

    def __init__(self, path, module, source, tree):
        self.path = path          # repo-relative posix path
        self.module = module      # dotted module name ("paddle_tpu.x.y")
        self.is_package = path.endswith("__init__.py")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        #: local name -> absolute dotted target ("pkg.mod" for module
        #: imports, "pkg.mod.attr" for from-imports)
        self.import_aliases = {}
        #: module-level NAME = "literal" string constants (env-var names,
        #: chaos site prefixes, ...)
        self.str_constants = {}
        #: qualname -> ast.FunctionDef; methods are "Class.method"
        self.functions = {}
        #: class name -> ast.ClassDef
        self.classes = {}
        self._harvest()

    def _harvest(self):
        mod_parts = self.module.split(".")
        # the package a relative import resolves against: for a module
        # file, one level up is its own package
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.str_constants[node.targets[0].id] = node.value.value
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # a package __init__'s module name IS its package
                    # (".__init__" was stripped), so level 1 resolves
                    # against the full name; a plain module drops its own
                    # leaf first
                    drop = node.level - (1 if self.is_package else 0)
                    base = mod_parts[:len(mod_parts) - drop]
                else:
                    base = []
                target = ".".join(base + (node.module.split(".")
                                          if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.import_aliases[a.asname or a.name] = \
                        f"{target}.{a.name}" if target else a.name
        # functions/classes with class-qualified names (one level deep is
        # all this codebase uses; nested defs keep their enclosing scope
        # out of the qualname on purpose — they are not call targets)
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.functions[f"{node.name}.{sub.name}"] = sub

    def line(self, lineno):
        """1-indexed source line ("" past EOF — decorators/multiline spans
        can report a line the splitlines list lacks when a file ends
        mid-statement)."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def resolve_str(self, node, index=None):
        """Resolve an expression to a string literal if statically
        possible: a Constant, a module-level NAME constant, or (given the
        index) an imported NAME constant from another indexed module."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.str_constants:
                return self.str_constants[node.id]
            target = self.import_aliases.get(node.id)
            if index is not None and target and "." in target:
                mod, _, name = target.rpartition(".")
                fi = index.by_module.get(mod)
                if fi is not None:
                    return fi.str_constants.get(name)
        return None


class ModuleIndex:
    """Every ``*.py`` under ``root``'s indexed packages, parsed once.

    ``root`` defaults to the repo root (the directory holding the
    ``paddle_tpu`` package this module was imported from), so the CLI works
    from any cwd; tests hand it a fixture tree instead.
    """

    PACKAGES = ("paddle_tpu", "scripts", "tests")

    def __init__(self, root=None, packages=None):
        if root is None:
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        self.root = root
        self.packages = tuple(packages or self.PACKAGES)
        self.files = {}        # rel posix path -> FileInfo
        self.by_module = {}    # dotted module -> FileInfo
        self.errors = []       # (path, SyntaxError) — reported, not fatal
        for pkg in self.packages:
            top = os.path.join(root, pkg)
            if not os.path.isdir(top):
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        self._add(os.path.join(dirpath, fn))

    def _add(self, abspath):
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            self.errors.append((rel, e))
            return
        module = rel[:-3].replace("/", ".")
        if module.endswith(".__init__"):
            module = module[:-len(".__init__")]
        fi = FileInfo(rel, module, source, tree)
        self.files[rel] = fi
        self.by_module[module] = fi

    # ---- queries rules share ---------------------------------------------
    def iter_files(self, prefix="paddle_tpu/"):
        """FileInfos whose path starts with ``prefix`` (or any of a tuple
        of prefixes), sorted by path."""
        if isinstance(prefix, str):
            prefix = (prefix,)
        for path in sorted(self.files):
            if any(path.startswith(p) for p in prefix):
                yield self.files[path]

    def doc(self, rel):
        """A non-indexed text file (docs/*.md) under root, or None."""
        p = os.path.join(self.root, rel)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def string_call_args(self, func_names, prefix=("paddle_tpu/",)):
        """All statically-resolvable string first-arguments to calls whose
        callee renders (by trailing attribute or bare name) to one of
        ``func_names``: ``{value: [(path, lineno), ...]}``. The shared
        harvest behind the registry-style rules (metric names, chaos
        sites, env names)."""
        out = {}
        for fi in self.iter_files(prefix):
            for node in ast.walk(fi.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                f = node.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None)
                if name not in func_names:
                    continue
                val = fi.resolve_str(node.args[0], index=self)
                if val is not None:
                    out.setdefault(val, []).append((fi.path, node.lineno))
        return out
