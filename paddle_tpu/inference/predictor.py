"""Predictor (reference: AnalysisPredictor in
paddle/fluid/inference/api/analysis_predictor.cc + the paddle_infer handle
API: get_input_names/get_input_handle/run/get_output_handle).

The predictor wraps either (a) a Layer instance (direct, the common in-process
path) or (b) a jit.save'd artifact directory. forward is jit-compiled once per
input signature — XLA's AOT compile IS the reference's pass pipeline.
"""
import numpy as np

from ..framework.core import Tensor, to_tensor


class _IOHandle:
    """Zero-copy style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        self._shape = tuple(shape)

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def share_external_data(self, arr):
        self._value = arr

    def copy_to_cpu(self):
        v = self._value
        if isinstance(v, Tensor):
            return np.asarray(v.numpy())
        return np.asarray(v)

    def shape(self):
        v = self._value
        return list(np.shape(v.numpy() if isinstance(v, Tensor) else v))


class Predictor:
    def __init__(self, config_or_layer, input_names=None):
        from ..nn.layer.layers import Layer

        self._jitted = {}
        if isinstance(config_or_layer, Layer):
            self._layer = config_or_layer
            self._layer.eval()
        else:
            config = config_or_layer
            # artifact path: a jit.save'd Layer is weights + descriptor; a
            # Layer instance must be supplied to bind them (the reference
            # deserializes a Program; our program is the traced Layer)
            raise ValueError(
                "create_predictor(Config) from serialized artifacts requires "
                "the model class; pass the Layer directly: "
                "create_predictor(layer) or Predictor(layer). For jit.save'd "
                "weights, build the Layer, layer.set_state_dict(paddle.jit."
                "load(path)['state_dict']), then Predictor(layer)."
            )
        self._input_names = list(input_names) if input_names else ["x"]
        self._inputs = {n: _IOHandle(n) for n in self._input_names}
        self._outputs = {}

    # -- handle API --------------------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        return self._outputs[name]

    # -- execution ---------------------------------------------------------
    def run(self, inputs=None):
        """Either positional (list of np arrays, paddle_infer v2 style) or via
        previously-filled input handles."""
        if inputs is not None:
            arrs = [np.asarray(a) for a in inputs]
        else:
            arrs = [self._inputs[n]._value for n in self._input_names]

        sig = tuple((a.shape, str(a.dtype)) for a in arrs)
        fn = self._jitted.get(sig)
        if fn is None:
            from ..jit_api import StaticLayer

            fn = StaticLayer(self._layer)
            self._jitted[sig] = fn
        out = fn(*[to_tensor(a) for a in arrs])
        outs = out if isinstance(out, (tuple, list)) else [out]
        self._outputs = {}
        results = []
        for i, o in enumerate(outs):
            h = _IOHandle(f"out_{i}")
            h._value = o
            self._outputs[h.name] = h
            results.append(np.asarray(o.numpy()) if isinstance(o, Tensor) else np.asarray(o))
        return results if inputs is not None else None

    # -- generation --------------------------------------------------------
    def generate(self, input_ids, **kwargs):
        """Autoregressive decode via the model's jitted KV-cache loop
        (GenerationMixin) — reference: AnalysisPredictor-driven generation."""
        if not hasattr(self._layer, "generate"):
            raise TypeError(f"{type(self._layer).__name__} has no generate()")
        out = self._layer.generate(to_tensor(input_ids), **kwargs)
        return np.asarray(out.numpy())

    def generate_speculative(self, input_ids, draft_model, **kwargs):
        """Draft-verify decoding through the predictor (exactly the target
        model's greedy stream; see GenerationMixin.generate_speculative)."""
        draft = draft_model._layer if isinstance(draft_model, Predictor) else draft_model
        out = self._layer.generate_speculative(to_tensor(input_ids), draft, **kwargs)
        return np.asarray(out.numpy())

    def serve(self, prompts, max_new_tokens=32, eos_token_id=None,
              max_seqs=4, page_size=64, num_pages=None, max_len=None,
              engine=None, **serve_kwargs):
        """Continuous-batching greedy serving over the paged KV pool
        (inference.continuous.ContinuousBatchingEngine): variable-length
        prompts queue, join mid-flight as slots/pages free, and each result
        equals that prompt's dense generate(). Pass `engine` to reuse a warm
        engine (compiled prefill/decode programs + pool) across calls."""
        from .continuous import ContinuousBatchingEngine

        if engine is None:
            if max_len is None:
                from ..generation import prompt_bucket

                longest = max(len(np.asarray(p).reshape(-1)) for p in prompts)
                # must cover BOTH the prefill bucket of the longest prompt
                # and its full decode extent, rounded to whole pages
                max_len = max(prompt_bucket(longest), longest + max_new_tokens)
                max_len = -(-max_len // page_size) * page_size
            engine = ContinuousBatchingEngine(
                self._layer, max_seqs=max_seqs, page_size=page_size,
                num_pages=num_pages, max_len=max_len)
        # sampling knobs / on_token streaming pass straight through
        return engine.serve(prompts, max_new_tokens, eos_token_id=eos_token_id,
                            **serve_kwargs)

    # -- AOT export (reference: save_optimized_model / Program serialization;
    # TPU-native: StableHLO via jax.export — the compiled artifact is
    # hardware-portable and reloadable without the model class) ------------
    def export_aot(self, path, *example_inputs):
        """Trace + lower the forward on example inputs and serialize the
        StableHLO artifact to `path`. Returns the byte count."""
        import jax
        from jax import export as jexport

        layer = self._layer
        state = layer.raw_state_dict()

        def pure(state, *args):
            out = layer.functional_call(
                {k: Tensor(v, stop_gradient=True) for k, v in state.items()},
                *[Tensor(a) for a in args],
                training=False,
            )
            outs = out if isinstance(out, (tuple, list)) else (out,)
            return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

        args = tuple(to_tensor(a)._data for a in example_inputs)
        from ..observability import compilemem as _compilemem

        with _compilemem.record_compile("predictor.export_aot",
                                        trigger="aot"):
            exp = jexport.export(jax.jit(pure))(state, *args)  # compile-ledger-ok
        data = exp.serialize()
        with open(path, "wb") as f:
            f.write(data)
        self._aot = (exp, state)
        return len(data)

    @staticmethod
    def load_aot(path):
        """Load a serialized AOT artifact; returns AotPredictor (call with
        the same state pytree + inputs signature used at export)."""
        from jax import export as jexport

        with open(path, "rb") as f:
            exp = jexport.deserialize(bytearray(f.read()))
        return AotPredictor(exp)

    def clone(self):
        return Predictor(self._layer, self._input_names)


class AotPredictor:
    """Runs a deserialized StableHLO export: state-free serving — the weights
    travel as the first pytree argument (reference: the deserialized
    inference Program + persistables)."""

    def __init__(self, exported):
        self._exported = exported

    def run(self, state, *inputs):
        args = tuple(to_tensor(a)._data for a in inputs)
        out = self._exported.call(state, *args)
        return [np.asarray(o) for o in (out if isinstance(out, (tuple, list)) else [out])]


def create_predictor(config_or_layer, input_names=None):
    return Predictor(config_or_layer, input_names)
