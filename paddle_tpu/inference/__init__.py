"""paddle.inference parity (reference: paddle/fluid/inference/ —
AnalysisPredictor + paddle_infer Python API in
python/paddle/inference/__init__.py: Config, create_predictor, Predictor,
zero-copy input/output handles).

TPU-native design (SURVEY.md §3.5): the reference's pass pipeline + executor
collapse into one AOT-compiled jax.jit callable — XLA is the optimizer
(fusion passes ≡ IR passes, buffer assignment ≡ memory-reuse pass, and the
TensorRT subgraph engine has no analogue because XLA compiles the WHOLE
graph). The Predictor keeps the zero-copy handle API shape so deployment
scripts port over.
"""
from .config import Config
from .continuous import ContinuousBatchingEngine
from .predictor import Predictor, create_predictor

__all__ = ["Config", "ContinuousBatchingEngine", "Predictor", "create_predictor"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PlaceType:
    kHOST = 0
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kCUSTOM = 3
