"""Inference Config (reference: paddle/fluid/inference/api/analysis_config.cc
— model paths, device selection, optimization toggles)."""


class Config:
    def __init__(self, model=None, params=None, model_dir=None):
        # accept both Config(prog_file, params_file) and Config(model_dir)
        if model is not None and params is None and model_dir is None:
            self._model_dir = model
            self._prog_file = None
        else:
            self._model_dir = model_dir
            self._prog_file = model
        self._params_file = params
        self._use_tpu = True
        self._precision = "float32"
        self._enable_memory_optim = True
        self._batch = 1
        self._extra = {}

    # -- device selection (CUDA-era APIs accepted; everything runs on TPU/XLA)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0, precision_mode=None):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def enable_xpu(self, *a, **k):
        self._use_tpu = True

    def enable_custom_device(self, device_type="tpu", device_id=0):
        self._use_tpu = True

    def use_gpu(self):
        return self._use_tpu

    def gpu_device_id(self):
        return 0

    # -- precision / optimization toggles
    def enable_tensorrt_engine(self, *a, precision_mode=None, **k):
        # XLA compiles the whole graph; precision hint is honored
        if precision_mode in ("Half", 1):
            self._precision = "float16"
        elif precision_mode in ("Bfloat16", 3):
            self._precision = "bfloat16"

    def tensorrt_engine_enabled(self):
        return False

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_mkldnn(self):
        pass

    # -- model paths
    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def set_model(self, model, params=None):
        if params is None:
            self._model_dir = model
        else:
            self._prog_file, self._params_file = model, params

    def summary(self):
        return (
            f"Config(model_dir={self._model_dir}, prog={self._prog_file}, "
            f"precision={self._precision}, backend=tpu/xla)"
        )
