"""Continuous-batching serving engine over the paged KV pool (reference
capability: paddle/fluid/inference AnalysisPredictor's serving class +
PaddleNLP block-attention / vLLM-style continuous batching; PAPERS.md
ragged-paged-attention).

TPU-native shape: compute is two jitted programs with STATIC shapes —
a bucketed PREFILL (compiled per prompt bucket, reusing the dense
fixed-cache path) whose KV lands in pool pages via a jitted insert, and a
single DECODE step over all `max_seqs` slots driving the model through
`PagedLayerCache` entries (kernel-backed paged attention on TPU). The
scheduler is plain host Python between jitted calls: retire finished
sequences, free their pages, admit queued requests into freed slots
mid-flight of everyone else — the continuous part. Memory is bounded by
the page pool, not by max_seqs × max_len:

- admission is reservation-based: a request enters only when
  ceil((true_len + max_new) / page_size) pages (and the prefill bucket's
  pages) are free, so decode can never deadlock on pool exhaustion;
- page 0 is scratch: inactive slots' page tables point at it, their
  writes land there harmlessly (lengths masks it out of every real row).

Decoding is greedy by default; serve(do_sample=True, ...) runs the dense
path's sampler math with per-request key streams (reproducible regardless
of co-scheduling). kv_cache_dtype="int8" switches the pool to the
QuantizedTensor layout the Pallas kernel consumes natively.

Data-plane pipeline (ISSUE 6): the engine overlaps host scheduling with
device compute instead of ping-ponging between them —

- **chunked prefill** (``prefill_chunk=``): a long prompt lands in
  page-aligned chunks scheduled BETWEEN decode blocks (each chunk is the
  prefix-cache machinery's gather + suffix-prefill over the pages already
  inserted), so a 2048-token prompt no longer stalls every co-tenant's
  TPOT for one monolithic bucketed dispatch, and the big prompt-bucket
  programs are replaced by a handful of chunk-shaped ones. Mid-prefill
  slots keep their page-table row at scratch, so concurrent decode
  dispatches can't write into half-built pages.
- **double-buffered async decode** (``async_decode=``): decode block k+1
  is dispatched chained off block k's device-resident last-token row
  BEFORE block k's tokens are read back; the host retire/admit/emit work
  for block k runs under block k+1's device execution. Lengths and key
  indices advance at dispatch time (identical to emit-time accounting for
  every surviving slot — retired slots are zeroed anyway), retirement and
  admission stay at readback points, and the in-flight depth is bounded
  at ONE so pool donation stays a single-owner chain.
- **lock decomposition**: jitted EXECUTION serializes per engine
  (``engine.dispatch_lock``); only first-TRACE of a program key takes the
  process-wide ``_COMPILE_LOCK`` (concurrent tracing of the shared
  model's programs leaks tracers through the framework's thread-oblivious
  Tensor state — executing already-compiled programs does not). N
  in-process replicas therefore genuinely run concurrently once warm.
"""
import hashlib
import math
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core as _core
from ..framework.core import Tensor
from ..generation import _make_sampler, prompt_bucket
from ..observability import compilemem as _compilemem
from ..observability import devprof as _devprof
from ..observability import goodput as _goodput
from ..observability import tracing as _trace
from ..observability.metrics import registry as _registry
from ..ops.paged_attention import PagedLayerCache
from ..ops.ragged_paged_attention import RaggedLayerCache
from ..testing import chaos
from ..utils.envs import env_bool as _env_bool
from ..utils.envs import env_int as _env_int
from ..utils.metrics_bus import counters
from ..utils.retry import RetryPolicy

# serving telemetry (the Gemma-on-TPU serving comparison's vocabulary,
# PAPERS.md): TTFT = serve-entry → first token per request; TPOT = decode
# dispatch wall / tokens in the block. Gauges carry high-water marks so a
# post-hoc snapshot still shows peak pressure. Always-on: per-request /
# per-dispatch observes are noise against a jitted model call.
_M_TTFT = _registry.histogram("serve.ttft_s")
_M_TPOT = _registry.histogram(
    "serve.tpot_s",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0, 2.5))
_M_QUEUE = _registry.gauge("serve.queue_depth")
_M_OCCUPANCY = _registry.gauge("serve.slot_occupancy")
_M_TOKENS = _registry.counter("serve.tokens_out")
_M_REQUESTS = _registry.counter("serve.requests")
_M_PREFIX_HIT = _registry.counter("serve.prefix.hit_pages")
_M_PREFIX_LOOKUP = _registry.counter("serve.prefix.lookup_pages")
# data-plane pipeline metrics (ISSUE 6): host time hidden under an
# in-flight decode dispatch, prefill chunks landed between decode blocks,
# and warmup()'s AOT compile wall (the spike the per-replica warmup keeps
# out of first requests)
_M_OVERLAP = _registry.histogram(
    "serve.dispatch_overlap_s",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0, 2.5))
_M_CHUNKS = _registry.counter("serve.prefill_chunks")
_M_WARMUP = _registry.histogram("serve.compile_warmup_s")
# page-pool fragmentation gauges (ISSUE 8): where the pool's pages are —
# truly free, held by the prefix cache (evictable), or referenced by
# in-flight requests — plus the cache-held fraction of reclaimable pages.
# The HBM ledger's kv_pool component says how BIG the pool is; these say
# how USED it is.
_M_POOL_FREE = _registry.gauge(
    "serve.pool_frag_free_pages", help="KV pool pages on the free list")
_M_POOL_EVICT = _registry.gauge(
    "serve.pool_frag_evictable_pages",
    help="refcount-0 prefix-cache pages (reclaimable, LRU-evictable)")
_M_POOL_USED = _registry.gauge(
    "serve.pool_frag_used_pages",
    help="pages referenced by in-flight requests")
_M_POOL_FRAG = _registry.gauge(
    "serve.pool_frag_ratio",
    help="cache-held fraction of reclaimable pages "
         "(evictable / (free + evictable))")

# one module-level jitted block-decode key builder (jit cache survives
# across serve() calls) over PER-REQUEST key bases (online mode admits
# requests with different seeds into one batch): bases [max_seqs, 2],
# idxs [k, max_seqs] -> keys [k, max_seqs, 2]. fold_in(fold_in(base, rid), i)
# == fold_in(key_base, i) with key_base = fold_in(base, rid), so the sampled
# streams are bit-identical to the pre-online single-seed
# fold_in(fold_in(seed_key, rid), i) scheme.
_KEYS_FROM_BASE = _compilemem.ledgered_jit(jax.vmap(
    jax.vmap(lambda kb, i: jax.random.fold_in(kb, i), in_axes=(0, 0)),
    in_axes=(None, 0)), key="serve.keys_from_base")

class _StampedRLock:
    """RLock that remembers WHEN its current outermost hold began.

    The serving monitor needs to tell apart two reasons a dispatcher's
    heartbeat goes stale while the process-wide dispatch lock is busy:
    the holder is legitimately inside a long first-compile (every other
    dispatcher queues behind it — nobody is dead), or the holder is wedged
    in a hung device call (nothing will ever progress — the stale replicas
    ARE dead and their work must relocate). A bare try-acquire can't
    distinguish them; the hold-start timestamp can: a hold younger than
    the hang deadline reads as compiling, older reads as wedged.

    It also tracks WHO participates — the holder's thread ident and the
    idents blocked in acquire() — so the monitor only credits the lock for
    a replica's silence when that replica's dispatcher is actually the
    holder or a waiter. A dispatcher wedged somewhere ELSE (post-lock host
    sync, a blocking user callback) must not ride out its death verdict on
    other threads' healthy compiles."""

    __slots__ = ("_lock", "_depth", "_since", "_holder", "_waiters")

    def __init__(self, name=None):
        self._lock = threading.RLock()
        if name is not None:
            # label for the runtime lock-order sanitizer
            # (testing/lockorder.py): the compile lock and every engine's
            # dispatch lock are all born on the line above, and the
            # sanitizer must keep them distinct order classes. A plain C
            # RLock (sanitizer off) has no __dict__ — stamping is free to
            # fail.
            try:
                self._lock._lo_name = name
            except AttributeError:
                pass
        self._depth = 0
        self._since = None  # monotonic start of the current outermost hold
        self._holder = None   # thread ident of the current holder
        self._waiters = set()  # thread idents blocked in acquire()

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        if blocking and self._holder != me:  # a reentrant acquire can't block
            self._waiters.add(me)  # set ops are atomic under the GIL
            try:
                # holder bookkeeping runs INSIDE the waiter window: a gap
                # where the winning thread is neither waiter nor holder
                # would let the monitor sample participants() in between
                # and kill a healthy replica that just won the lock
                return self._acquired(me, self._lock.acquire(blocking,
                                                             timeout))
            finally:
                self._waiters.discard(me)
        return self._acquired(me, self._lock.acquire(blocking, timeout))

    def _acquired(self, me, ok):
        if ok:
            self._depth += 1
            if self._depth == 1:
                self._since = time.monotonic()
                self._holder = me
        return ok

    def release(self):
        # fields mutate only while the lock is held (single writer); the
        # monitor's unlocked held_since()/participants() reads are benign
        # torn-free races
        self._depth -= 1
        if self._depth == 0:
            self._since = None
            self._holder = None
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc_info):
        self.release()

    def held_since(self):
        """Monotonic timestamp of the current outermost acquire, or None
        when free. Advisory (read without the lock)."""
        return self._since

    def participants(self):
        """Thread idents currently holding OR blocked acquiring the lock.
        Advisory snapshot (read without the lock)."""
        out = set(self._waiters)
        holder = self._holder
        if holder is not None:
            out.add(holder)
        return out


#: Process-wide COMPILE lock: the serving frontend drives one engine per
#: dispatcher THREAD, and concurrent jit TRACING of the shared model's
#: programs leaks tracers through the framework's (thread-oblivious)
#: Tensor state. Only first-trace needs the global lock — each engine's
#: program keys are explicit (bucket/sampling/k), so once a key has run
#: successfully every later call is a jit cache hit executing compiled
#: code, which is thread-safe. Execution serializes per engine on
#: ``engine.dispatch_lock`` instead (the engine is single-threaded by
#: contract; the per-engine lock exists so the frontend's liveness
#: monitor can tell a dispatcher wedged in a device call from one queued
#: behind a neighbor's compile). This replaces the pre-ISSUE-6
#: process-wide ``_DISPATCH_LOCK`` that serialized every jitted call of
#: every replica behind one lock.
_COMPILE_LOCK = _StampedRLock(name="inference.compile_lock")

#: canonical greedy sampling tuple — every greedy request shares ONE
#: compiled prefill/decode program regardless of the knob values passed
GREEDY_SAMPLING = (False, 1.0, 0, 1.0)


def canonical_sampling(do_sample, temperature=1.0, top_k=0, top_p=1.0):
    return (GREEDY_SAMPLING if not do_sample else
            (True, float(temperature), int(top_k), float(top_p)))


class EngineRequest:
    """One request's full lifecycle state — the unit the online serving
    control plane (paddle_tpu/serving) hands to the engine and the engine
    hands back finished. ``serve()`` builds these internally, so the batch
    path and the frontend path exercise the SAME admission/decode/retire
    machinery.

    Result surface (the per-request failure-reason contract): exactly one of
    ``result`` (np.int32 array, prompt + generated tokens) or ``error`` (the
    exception that failed the request; ``error_message`` is its rendered
    string) is set once ``finished`` is True. ``timed_out`` requests retire
    with a partial ``result``.
    """

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "sampling", "seed", "timeout_s", "on_token", "adapter",
                 "tokens", "n_generated", "n_dispatched", "last_token",
                 "pages", "slot", "key_base", "t_enqueue", "t_admit",
                 "t_first_token", "t_done", "error", "result", "finished",
                 "timed_out", "cancelled", "trace")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id=None,
                 sampling=GREEDY_SAMPLING, seed=0, timeout_s=None,
                 on_token=None, adapter=None):
        self.rid = int(rid)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            # admission always produces the prefill's first token, so a
            # 0-token budget can't be honored — reject it at construction
            # (submit()/serve() callers both reach this) instead of decoding
            # past the page reservation
            raise ValueError(
                f"request {self.rid}: max_new_tokens must be >= 1, got "
                f"{self.max_new_tokens}")
        self.eos_token_id = eos_token_id
        self.sampling = tuple(sampling)
        self.seed = int(seed)
        self.timeout_s = timeout_s
        self.on_token = on_token
        # resolved serving.adapters.LoRAAdapter (or None): the low-rank
        # LM-head delta this request decodes under. An object, never a
        # name — registry resolution/refcounting is the frontend's job
        self.adapter = adapter
        self.tokens = []          # prompt + generated, filled at admission
        self.n_generated = 0
        # tokens DISPATCHED to the device (>= n_generated while a decode
        # block is in flight): the async pipeline builds block k+1's key
        # indices and fed lengths from this before block k's tokens are
        # read back. For surviving slots it always equals what emit-time
        # accounting would produce; retired slots discard the overshoot.
        self.n_dispatched = 0
        self.last_token = None
        self.pages = []
        self.slot = None
        self.key_base = None      # np uint32[2], lazily built at admission
        self.t_enqueue = time.monotonic()  # TTFT epoch
        self.t_admit = None
        self.t_first_token = None
        self.t_done = None
        self.error = None
        self.result = None
        self.finished = False
        self.timed_out = False
        self.cancelled = False    # set by the frontend; honored at the next
        # block boundary (the request retires with a partial result)
        # request-scoped tracing (ISSUE 7): the frontend's per-attempt span
        # handle — the engine's admit/prefill/decode spans nest under it.
        # None on the batch serve() path / when telemetry is off; a reroute
        # clone gets the NEW attempt's span from the frontend.
        self.trace = None

    @property
    def error_message(self):
        """Failure reason as a string, or None (satellite: rid -> reason)."""
        if self.error is None:
            return None
        return f"{type(self.error).__name__}: {self.error}"

    def clone_for_retry(self):
        """A fresh, un-admitted copy for rerouting to another replica after
        this one's replica died mid-flight. Keeps rid/seed so the sampled
        key stream — hence the output — is identical on the new replica,
        and t_enqueue so TTFT/queue-wait span the whole journey including
        the time lost on the dead replica (the failover tail is exactly
        what the per-SLO histograms exist to expose)."""
        clone = EngineRequest(self.rid, self.prompt, self.max_new_tokens,
                              eos_token_id=self.eos_token_id,
                              sampling=self.sampling, seed=self.seed,
                              timeout_s=self.timeout_s,
                              on_token=self.on_token, adapter=self.adapter)
        clone.t_enqueue = self.t_enqueue
        return clone


def _row_sampler(do_sample, temperature, top_k, top_p):
    """Per-ROW sampler: each slot consumes its own PRNG key stream, so a
    sequence's sampled tokens do not depend on which other requests happen
    to share the batch (continuous batching reorders co-tenants freely).
    Reuses the dense path's sampler math (generation._make_sampler)."""
    base = _make_sampler(do_sample, temperature, top_k, top_p,
                         repetition_penalty=1.0, min_length=0,
                         eos_token_id=None)
    if not do_sample:
        return lambda logits, keys: base(logits, None)
    return jax.vmap(lambda lg, k: base(lg[None], k)[0])


class _PrefillState:
    """One slot mid-chunked-prefill: the full page reservation plus how
    many of those pages already hold valid KV. The engine's page_table row
    and lengths entry stay ZERO until graduation, so decode dispatches
    running between chunks write this slot's fed token to the scratch page
    instead of into half-built pages."""

    __slots__ = ("req", "pages", "filled_pages", "n_pre0", "digests",
                 "consumed")

    def __init__(self, req, pages, n_pre, digests):
        self.req = req
        self.pages = pages          # full reservation (shared + new)
        self.filled_pages = n_pre   # pages holding valid KV (page-aligned)
        self.n_pre0 = n_pre         # prefix-cache hit width at admission
        self.digests = digests      # prompt-page digest chain (for indexing)
        # ragged mode: prompt TOKENS already streamed into the pool
        # (token-granular — ragged chunks need no page alignment); the
        # legacy chunk path keeps its page-granular filled_pages instead
        self.consumed = None


class _InflightBlock:
    """One dispatched-but-not-read-back decode block: the device token
    array, the slot→request mapping frozen at dispatch time, and the
    device-resident last-step row the NEXT block's feed chains from."""

    __slots__ = ("blk", "last", "k", "rows", "t0", "host", "cold")

    def __init__(self, blk, last, k, rows, t0, host=None, cold=False):
        self.blk = blk      # device [k, max_seqs] token block
        self.last = last    # device [max_seqs, 1] last-step tokens
        self.k = k
        self.rows = rows    # [(slot, req)] active at dispatch
        self.t0 = t0
        self.host = host    # sync mode: tokens already read back in-lock
        self.cold = cold    # dispatched under a first-trace (compile) hold


class ContinuousBatchingEngine:
    def __init__(self, model, max_seqs=4, page_size=16, num_pages=None,
                 max_len=512, kv_cache_dtype=None, decode_block=8,
                 enable_prefix_cache=False, prefill_chunk=None,
                 async_decode=True, dispatch_lock=None, ragged=None):
        cfg = model.config
        self.model = model
        model.eval()
        # device-time profiling plane (ISSUE 17): PADDLE_DEVPROF=1 samples
        # one timed decode dispatch per cadence window; disabled, the
        # dispatch path pays one is-None check
        _devprof.arm_from_env()
        self.max_seqs = max_seqs
        self.page_size = page_size
        self.max_len = max_len
        self.pages_per_seq = -(-max_len // page_size)  # page-table width
        # default pool = dense equivalent; callers size it down to the
        # expected occupancy — that is the memory win
        self.num_pages = num_pages or (1 + max_seqs * self.pages_per_seq)
        if self.num_pages < 2:
            raise ValueError("need at least one scratch + one real page")
        dtype = next(iter(model.parameters())).dtype
        Hkv, D, L = cfg.num_key_value_heads, cfg.head_dim, cfg.num_hidden_layers
        self.kv_cache_dtype = kv_cache_dtype
        if kv_cache_dtype == "int8":
            # int8 KV pool (jax paged_attention QuantizedTensor layout):
            # ~4x fewer HBM bytes per decode step vs f32, ~2x vs bf16 —
            # the decode-bandwidth lever; scales are per (head, page, row)
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                quantization_utils as qu,
            )

            def zero_pool():
                return qu.QuantizedTensor(
                    weight=jnp.zeros((Hkv, self.num_pages, page_size, D), jnp.int8),
                    scales=jnp.ones((Hkv, self.num_pages, page_size, 1), jnp.float32),
                )

            self.pools = [(zero_pool(), zero_pool()) for _ in range(L)]
        elif kv_cache_dtype not in (None, "model"):
            raise ValueError(f"unsupported kv_cache_dtype {kv_cache_dtype!r}")
        else:
            self.pools = [
                (jnp.zeros((Hkv, self.num_pages, page_size, D), dtype),
                 jnp.zeros((Hkv, self.num_pages, page_size, D), dtype))
                for _ in range(L)
            ]
        self.free_pages = list(range(1, self.num_pages))  # page 0 = scratch
        self.free_slots = list(range(max_seqs))
        self.page_table = np.zeros((max_seqs, self.pages_per_seq), np.int32)
        self.lengths = np.zeros(max_seqs, np.int32)
        self._prefill_fns = {}
        self._insert_fns = {}
        self._decode_fns = {}
        self._decode_block_fns = {}
        # ---- automatic prefix caching (vLLM-class; PAPERS.md ragged paged
        # attention context). Content-addressed FULL prompt pages: a page
        # holding tokens [j*bs, (j+1)*bs) of some prompt is indexed by the
        # exact byte string of the prompt's first (j+1)*bs tokens, so a later
        # request sharing that prefix points its page table at the SAME page
        # (refcounted) and prefills only its suffix — attention over the
        # shared prefix is served by a jitted page-gather instead of
        # recompute. Pages with refcount 0 stay cached (LRU-evictable) until
        # the allocator needs them. Shared pages are never written: decode
        # writes at positions >= true_len and the match is capped at
        # (true_len-1)//bs pages, so every write lands in a private page.
        # ---- data-plane pipeline knobs (ISSUE 6) --------------------------
        # prefill_chunk: page-aligned token count per prefill chunk; None/0
        # disables chunking (monolithic bucketed prefill, the legacy path).
        # Prompts whose post-prefix suffix fits one chunk still prefill
        # monolithically — chunking only changes behavior for longer ones.
        if prefill_chunk:
            if kv_cache_dtype == "int8":
                # chunk j re-reads earlier chunks' KV through the pool; an
                # int8 pool would make that read lossy while the monolithic
                # path attends to exact float KV — refuse rather than break
                # the engine's exact-equality contract (same rule as the
                # prefix cache)
                raise ValueError("prefill_chunk does not compose with "
                                 "kv_cache_dtype='int8' (lossy chunk "
                                 "re-reads would change outputs vs the "
                                 "monolithic path)")
            prefill_chunk = max(int(prefill_chunk) // page_size, 1) * page_size
        self.prefill_chunk = int(prefill_chunk or 0)
        self.async_decode = bool(async_decode)
        # per-engine execution lock (injectable so bench_serving.py can
        # reproduce the pre-ISSUE-6 process-wide lock by sharing one
        # instance across baseline engines); first-trace additionally takes
        # the global _COMPILE_LOCK — see _locked_dispatch()
        self.dispatch_lock = dispatch_lock or _StampedRLock(
            name="inference.dispatch_lock")
        self._warm = set()          # program keys that have run successfully
        self._last_dispatch_cold = False  # last _locked_dispatch traced?
        self._prefilling = {}       # slot -> _PrefillState (chunked prefill)
        self._inflight = None       # the ONE in-flight _InflightBlock
        # requests retired while an out-of-band caller (export_pages'
        # _settle_inflight) processed the in-flight block: step() returns
        # them on its next call so the frontend still finishes every handle
        self._pending_retired = []
        self.enable_prefix_cache = bool(enable_prefix_cache)
        if self.enable_prefix_cache and kv_cache_dtype == "int8":
            # a shared prefix would be re-read through the lossy int8
            # pool while the no-cache path attends to exact float KV —
            # silently divergent outputs near argmax ties; refuse rather
            # than break the engine's exact-equality contract
            raise ValueError("enable_prefix_cache does not compose with "
                             "kv_cache_dtype='int8' (lossy prefix KV would "
                             "change outputs vs the uncached path)")
        # hashed prefix-page index (ISSUE 6 satellite): keys are CHAINED
        # 16-byte blake2b digests — digest[j] = H(digest[j-1] || page j's
        # token bytes) — so indexing or probing a whole prompt costs
        # O(prompt bytes) total instead of the old O(pages^2) re-hash of
        # the full prefix per page (which made Router.place()'s affinity
        # probe quadratic in prompt length). A digest collision would
        # false-match foreign KV; at 128 bits that is beyond-cosmic-ray
        # territory, and tests assert the probe equals a content-exact
        # oracle over real workloads.
        self._prefix_index = {}   # chained page digest -> page_id
        self._page_hash = {}      # page_id -> digest (indexed pages)
        self._page_refs = {}      # page_id -> refcount (in-use pages)
        from collections import OrderedDict

        self._evictable = OrderedDict()  # page_id -> None; LRU order
        self._gather_fns = {}
        self._prefill_suffix_fns = {}
        self._cache_weights_version = None
        # decode_block: max decode steps fused into ONE device dispatch
        # (lax.scan). Each dispatch costs a full host→device round trip —
        # ~1.3s through the axon tunnel (PROFILE.md r5) — so per-token
        # dispatch makes serving latency-bound at any model size. Trade-off:
        # retirement/admission (and on_token streaming) happen at block
        # boundaries, and a sequence hitting EOS mid-block wastes the rest of
        # the block's compute for its slot. 1 restores per-token behavior.
        self.decode_block = max(int(decode_block), 1)
        # observability for tests/bench: peak pages in use, deferred admits,
        # and the degradation counters (failed/timed-out requests keep their
        # co-tenants serving — see serve())
        self.stats = {"peak_pages": 0, "deferred_admissions": 0,
                      "decode_steps": 0, "prefix_hit_pages": 0,
                      "prefix_evictions": 0, "failed_requests": 0,
                      "timed_out_requests": 0}
        # per-serve map rid -> exception for requests that failed in
        # isolation (their results entry is None); the EngineRequest carries
        # the same exception + its rendered string for the online path.
        # Bounded so a long-running online engine can't grow it forever.
        self.request_errors = {}
        self._request_errors_bound = 1024
        # ---- online-serving state (frontend-driven mode) ------------------
        # slot -> EngineRequest. serve() uses the same machinery, so batch
        # and online requests share one admission/decode/retire path.
        self._active = {}
        # all co-scheduled requests share ONE sampling tuple (the sampler is
        # a compile-time constant of the decode program); admission defers
        # requests whose sampling differs from the running group's
        self._active_sampling = None
        # ---- per-request LoRA plane (ISSUE 19) ----------------------------
        # The decode group's adapter RANK is a compile-time constant of the
        # lora decode programs (like sampling); the adapter WEIGHTS are
        # runtime operands — per-row indices gather stacked [slots+1, ...]
        # A/B tensors inside the program, slot 0 all-zeros so no-adapter
        # rows ride along bit-identically (+0.0 delta). None = base group:
        # the untouched pre-LoRA programs, byte-for-byte.
        self._active_lora_rank = None
        self._slot_adapter = {}   # slot -> LoRAAdapter (adapter rows only)
        self._lora_slots = _env_int("PADDLE_LORA_SLOTS", 4)
        self._lora_device = OrderedDict()   # digest -> (a_dev, b_dev); LRU
        self._lora_stack_cache = OrderedDict()  # (rank, digests) -> stacks
        self._lora_prefill_fns = {}
        self._lora_suffix_fns = {}
        self._lora_decode_fns = {}
        self._lora_block_fns = {}
        self._lora_dims = (getattr(cfg, "hidden_size", None),
                           getattr(cfg, "vocab_size", None))
        # ---- ragged dispatch plane (ISSUE 20) -----------------------------
        # One packed [T]-token forward carries every prefill chunk AND every
        # decode row per step (ops/ragged_paged_attention.py), so the
        # per-bucket program ladder (prefill[b]/suffix[p,b]/insert[b]/
        # gather[p] × sampling × rank) collapses to ONE mixed program plus
        # the fixed-k decode block per (sampling, kv-dtype, lora-rank).
        # PADDLE_SERVING_RAGGED=0 is the kill switch: every legacy path is
        # byte-for-byte untouched when off. Ragged needs the split
        # trunk/head call (model.llama), so non-llama models fall back.
        if ragged is None:
            ragged = _env_bool("PADDLE_SERVING_RAGGED", True)
        self._ragged = bool(ragged) and getattr(model, "llama", None) is not None
        # token budget for prompt chunks per mixed dispatch (token-granular:
        # ragged writes need no page alignment, unlike legacy prefill_chunk)
        self._ragged_chunk = max(self.prefill_chunk or min(256, max_len), 1)
        # packed token-stream width: chunk budget + one feed token per slot
        self._ragged_tokens = self._ragged_chunk + max_seqs
        self._ragged_fns = {}        # sampling -> mixed program
        self._lora_ragged_fns = {}   # (sampling, rank) -> mixed lora program
        # O(1) maintained pages-in-use counter (satellite: replaces the
        # derived scan; tests assert it equals the scan at quiet points)
        self._pages_in_use = 0
        # (mutation_version, state_dict) captured at the last admission;
        # step() reuses it so the TPOT-critical loop never pays the full
        # parameter-tree walk per decode block (the batch path captured
        # state once per serve() — this keeps the online path at parity)
        self._decode_state_cache = None
        # HBM budget ledger + OOM forensics (ISSUE 8): the KV page pool is
        # a first-class component of the device memory budget, and an OOM
        # report must say what the engine was serving when it died. Both
        # registrations are weak — a dropped engine vanishes from reports.
        _compilemem.memory.register_component_provider(
            "kv_pool", self, "pool_bytes")
        _compilemem.register_oom_context(
            "serving_engine", self, "_oom_context")

    def _oom_context(self):
        """Serving-state snapshot for telemetry/oom_report.json."""
        return {
            "active_slots": len(self._active),
            "prefilling_slots": len(self._prefilling),
            "max_seqs": self.max_seqs,
            "pages_in_use": self._pages_in_use,
            "free_pages": len(self.free_pages),
            "evictable_pages": len(self._evictable),
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "pool_bytes": self.pool_bytes(),
            "inflight_block": self._inflight is not None,
            "stats": dict(self.stats),
        }

    def clear_prefix_cache(self):
        """Drop all cached (refcount-0) prefix pages and their index. In-use
        pages are untouched — they free normally on retire (their index
        entries are already gone, so they cannot be matched again)."""
        for pid in list(self._evictable):
            self.free_pages.append(pid)
        self._evictable.clear()
        self._prefix_index.clear()
        self._page_hash.clear()

    # ---- prefix-cache page accounting -------------------------------------
    def _available_pages(self):
        return len(self.free_pages) + len(self._evictable)

    def _alloc_pages(self, n):
        """Take n pages: free list first, then LRU-evict cached ones."""
        out = []
        for _ in range(n):
            if self.free_pages:
                out.append(self.free_pages.pop())
                continue
            pid, _ = self._evictable.popitem(last=False)  # LRU
            key = self._page_hash.pop(pid)
            self._prefix_index.pop(key, None)
            self.stats["prefix_evictions"] += 1
            out.append(pid)
        return out

    def _ref_pages(self, pages):
        for p in pages:
            n = self._page_refs.get(p, 0)
            if n == 0:
                self._pages_in_use += 1
            self._page_refs[p] = n + 1
            self._evictable.pop(p, None)

    def _unref_pages(self, pages):
        for p in pages:
            self._page_refs[p] -= 1
            if self._page_refs[p] == 0:
                del self._page_refs[p]
                self._pages_in_use -= 1
                if p in self._page_hash:  # cached: keep KV, evict lazily
                    self._evictable[p] = None
                else:
                    self.free_pages.append(p)

    def pages_in_use(self):
        """Referenced (in-flight) pages, maintained O(1) at every ref/unref
        transition — the admit loop's pressure signal and the router's load
        input. Equals ``num_pages - 1 - free - evictable`` (asserted in
        tests)."""
        return self._pages_in_use

    def _page_digests(self, prompt, n_pages):
        """Chained per-page digests for the first ``n_pages`` full pages:
        digest[j] identifies prompt[:(j+1)*bs] but costs O(bs) to extend,
        so the whole chain is O(prompt bytes) — the index/probe key that
        replaced the old quadratic full-prefix re-hash."""
        bs = self.page_size
        out, h = [], b""
        for j in range(n_pages):
            h = hashlib.blake2b(prompt[j * bs:(j + 1) * bs].tobytes(),
                                key=h, digest_size=16).digest()
            out.append(h)
        return out

    def _match_prefix(self, prompt, true_len):
        """Longest run of indexed full pages, capped so >=1 suffix token
        remains to prefill (its logits produce the first sampled token).
        Returns (n, shared pages, the full digest chain — reused by
        _index_prompt_pages so each admission hashes the prompt once)."""
        bs = self.page_size
        p_max = (true_len - 1) // bs
        digests = self._page_digests(prompt, true_len // bs)
        shared = []
        for j in range(p_max):
            pid = self._prefix_index.get(digests[j])
            if pid is None:
                break
            shared.append(pid)
        return len(shared), shared, digests

    def prefix_match_pages(self, prompt):
        """How many full prompt pages this engine could serve from its
        prefix cache right now (read-only: no refcounts taken, no state
        touched). The router's affinity signal — O(prompt bytes) digest
        chain + dict probes only, safe to call from the frontend's submit
        thread while the dispatcher runs."""
        if not self.enable_prefix_cache:
            return 0
        p = np.asarray(prompt, np.int32).reshape(-1)
        n, _, _ = self._match_prefix(p, len(p))
        return n

    def _index_prompt_pages(self, true_len, pages, start, digests):
        """Register this request's full prompt pages (from page `start` on;
        earlier ones were matched, hence already indexed). ``digests`` is
        the chain _match_prefix computed at admission."""
        bs = self.page_size
        for j in range(start, len(pages)):
            if (j + 1) * bs > true_len:
                break
            key = digests[j]
            if key not in self._prefix_index:  # first writer wins
                self._prefix_index[key] = pages[j]
                self._page_hash[pages[j]] = key

    # ---- prefix-cache jitted pieces ---------------------------------------
    def _gather_prefix(self, n_pages):
        """pools + page ids [n_pages] -> dense prefix KV [L, n*bs, Hkv, D]."""
        fn = self._gather_fns.get(n_pages)
        if fn is not None:
            return fn
        bs = self.page_size

        def read(pool, page_ids):
            # float pools only: int8 + prefix cache is refused in __init__
            arr = pool[:, page_ids]
            # [Hkv, n, bs, D] -> [n*bs, Hkv, D]
            arr = jnp.transpose(arr, (1, 2, 0, 3))
            return arr.reshape(n_pages * bs, arr.shape[2], arr.shape[3])

        def gather(pools, page_ids):
            ks = jnp.stack([read(kp, page_ids) for kp, _ in pools])
            vs = jnp.stack([read(vp, page_ids) for _, vp in pools])
            return ks, vs

        fn = self._gather_fns[n_pages] = _compilemem.ledgered_jit(
            gather, key=f"serve.gather[p{n_pages}]")
        _compilemem.ledger.note_cache_size("serve.gather",
                                           len(self._gather_fns))
        return fn

    def _prefill_suffix(self, n_prefix_pages, suffix_bucket, sampling):
        """Prefill ONLY the suffix, attending to the gathered prefix KV via
        the model's fixed-cache path (cache_position = prefix length, whose
        absolute-position mask handles the offset). Compiled per
        (prefix-page-count, suffix bucket, sampling) — repeated system
        prompts hit a handful of distinct prefix lengths, so the program
        cache stays small."""
        key3 = (n_prefix_pages, suffix_bucket, sampling)
        fn = self._prefill_suffix_fns.get(key3)
        if fn is not None:
            return fn
        model = self.model
        sampler = _row_sampler(*sampling)
        plen = n_prefix_pages * self.page_size

        def prefill_suf(state, ks_pre, vs_pre, ids_suf, suf_len, key):
            overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
            caches = model.init_cache(1, plen + suffix_bucket)
            wrapped = []
            for l, (kc, vc) in enumerate(caches):
                kc = kc.at[0, :plen].set(ks_pre[l].astype(kc.dtype))
                vc = vc.at[0, :plen].set(vs_pre[l].astype(vc.dtype))
                wrapped.append((Tensor(kc), Tensor(vc)))
            logits, presents = model.functional_call(
                overrides, Tensor(ids_suf), past_key_values=wrapped,
                cache_position=Tensor(jnp.int32(plen)), use_cache=True,
                training=False,
            )
            last = jax.lax.dynamic_index_in_dim(logits._data, suf_len - 1,
                                                axis=1, keepdims=False)
            tok0 = sampler(last, key[None])[0].astype(jnp.int32)
            ks = jnp.stack([p[0]._data[0, plen:] for p in presents])
            vs = jnp.stack([p[1]._data[0, plen:] for p in presents])
            return tok0, ks, vs

        fn = self._prefill_suffix_fns[key3] = _compilemem.ledgered_jit(
            prefill_suf,
            key=f"serve.suffix[p{n_prefix_pages},b{suffix_bucket},"
                f"s{sampling}]")
        _compilemem.ledger.note_cache_size("serve.suffix",
                                           len(self._prefill_suffix_fns))
        return fn

    # ---- dispatch locking -------------------------------------------------
    @contextmanager
    def _locked_dispatch(self, *keys):
        """Guard a jitted section. Warm program keys take only this
        engine's execution lock; any cold key additionally takes the
        process-wide compile lock for the duration (first call = trace).
        Keys are marked warm only after the section SUCCEEDS, so a
        retried transient failure recompiles under the lock again.
        ``_last_dispatch_cold`` records whether THIS section traced — the
        serving-goodput split attributes cold sections to 'compile'
        instead of prefill/decode."""
        cold = [k for k in keys if k not in self._warm]
        self._last_dispatch_cold = bool(cold)
        try:
            if not cold:
                with self.dispatch_lock:
                    chaos.site("obs.oom")
                    yield
                return
            with _COMPILE_LOCK, self.dispatch_lock:
                chaos.site("obs.oom")
                yield
            self._warm.update(cold)
        except Exception as e:
            # OOM-forensics seam (ISSUE 8): every engine dispatch —
            # prefill, gather/suffix, insert, decode — funnels through
            # here, so one interception covers them all. The report
            # commits (ledger + HBM budget + active slots/pages) before
            # the exception continues into the per-request isolation /
            # replica-death machinery.
            _compilemem.maybe_oom_report(
                e, program=str(keys[0]) if keys else None)
            raise

    def _xprof_annotation(self, req):
        """Host-side profiler annotation carrying the request's trace_id
        (``rtrace:<id>``): xprof's trace viewer shows it on the host
        timeline aligned with the device ops this dispatch enqueued — the
        join key between request traces and device profiles. Per-request
        program metadata is impossible (programs are compiled once per
        bucket and shared across requests), so the correlation is by host
        timeline, not op name. No-op without a trace."""
        if req.trace is None:
            return nullcontext()
        try:
            return jax.profiler.TraceAnnotation(
                f"rtrace:{req.trace.ctx.trace_id}")
        except Exception:
            return nullcontext()

    def _captured_state(self):
        """The version-checked raw_state_dict capture shared by admission
        and decode — keeps the O(n_params) tree walk off the latency-
        critical loop. Version read BEFORE the capture: a mutation landing
        in between tags fresh state with a stale version, which merely
        forces an extra refresh next time — never a stale serve.

        The refresh happens under the COMPILE lock: a sibling replica
        tracing the shared model temporarily rebinds its state through the
        framework's thread-oblivious Tensor plumbing, and a concurrent
        raw_state_dict() walk would capture those tracers (then feed them
        to a compiled program — the exact leak the old process-wide
        dispatch lock hid). Cache hits stay lock-free: a cached capture
        was taken outside any trace window and holds real arrays."""
        ver = _core.tensor_mutation_version()
        cache = self._decode_state_cache
        if cache is not None and cache[0] == ver:
            return cache[1]
        with _COMPILE_LOCK:
            state = self.model.raw_state_dict()
        self._decode_state_cache = (ver, state)
        return state

    # ---- jitted pieces ----------------------------------------------------
    def _prefill(self, bucket, sampling):
        fn = self._prefill_fns.get((bucket, sampling))
        if fn is not None:
            return fn
        model = self.model
        sampler = _row_sampler(*sampling)

        def prefill(state, ids_p, true_len, key):
            overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
            caches = model.init_cache(1, bucket)
            wrapped = [(Tensor(kc), Tensor(vc)) for kc, vc in caches]
            logits, presents = model.functional_call(
                overrides, Tensor(ids_p), past_key_values=wrapped,
                cache_position=Tensor(jnp.int32(0)), use_cache=True,
                training=False,
            )
            last = jax.lax.dynamic_index_in_dim(logits._data, true_len - 1,
                                                axis=1, keepdims=False)  # [1, V]
            tok0 = sampler(last, key[None])[0].astype(jnp.int32)
            ks = jnp.stack([p[0]._data[0] for p in presents])  # [L, S0b, Hkv, D]
            vs = jnp.stack([p[1]._data[0] for p in presents])
            return tok0, ks, vs

        fn = self._prefill_fns[(bucket, sampling)] = _compilemem.ledgered_jit(
            prefill, key=f"serve.prefill[b{bucket},s{sampling}]")
        _compilemem.ledger.note_cache_size("serve.prefill",
                                           len(self._prefill_fns))
        return fn

    @staticmethod
    def _pages_for_bucket(bucket, bs):
        return -(-bucket // bs)  # ceil: a bucket smaller than a page still needs one

    def _insert(self, bucket):
        """Scatter a bucket's dense prefill KV into this slot's pool pages.
        The bucket is padded up to a whole number of pages (a 16-token
        bucket under page_size=64 still writes one page; the pad region is
        masked out by `lengths` everywhere)."""
        fn = self._insert_fns.get(bucket)
        if fn is not None:
            return fn
        bs = self.page_size
        npg = self._pages_for_bucket(bucket, bs)
        pad = npg * bs - bucket

        from ..ops.paged_attention import is_quantized

        def write_page(pool, pid, chunk):
            if is_quantized(pool):
                from jax.experimental.pallas.ops.tpu.paged_attention import (
                    quantization_utils as qu,
                )

                qt = qu.quantize_to_int8(chunk.astype(jnp.float32))
                return type(pool)(
                    weight=pool.weight.at[:, pid].set(qt.weight),
                    scales=pool.scales.at[:, pid].set(qt.scales),
                )
            return pool.at[:, pid].set(chunk.astype(pool.dtype))

        def insert(pools, ks, vs, page_ids):
            if pad:
                ks = jnp.pad(ks, ((0, 0), (0, pad), (0, 0), (0, 0)))
                vs = jnp.pad(vs, ((0, 0), (0, pad), (0, 0), (0, 0)))
            out = []
            for l, (kp, vp) in enumerate(pools):
                for j in range(npg):
                    chunk_k = jnp.swapaxes(ks[l, j * bs:(j + 1) * bs], 0, 1)
                    chunk_v = jnp.swapaxes(vs[l, j * bs:(j + 1) * bs], 0, 1)
                    kp = write_page(kp, page_ids[j], chunk_k)
                    vp = write_page(vp, page_ids[j], chunk_v)
                out.append((kp, vp))
            return tuple(out)

        # donate the pool: the engine discards the pre-insert buffers
        # immediately, and without donation XLA copies the whole pool
        fn = self._insert_fns[bucket] = _compilemem.ledgered_jit(
            insert, key=f"serve.insert[b{bucket}]", donate_argnums=(0,))
        _compilemem.ledger.note_cache_size("serve.insert",
                                           len(self._insert_fns))
        return fn

    # Per-row length CAPS (ISSUE 6): the block size is chosen from the
    # LARGEST remaining token budget in the batch, so rows with smaller
    # budgets ride past their budget inside the block (their overshoot
    # tokens are discarded at emit). The cap — true_len + max_new - 1, the
    # last page-reserved position — is clamped INSIDE the program so an
    # overshooting row freezes its write position at its last reserved
    # slot instead of writing past its reservation. For every row within
    # budget the clamp is the identity, so outputs stay bit-identical to
    # the uncapped program. Without this, one short-budget co-tenant drags
    # the whole batch's block size down to its own remaining count (the
    # k-fragmentation that measured 2x extra dispatches under staggered
    # chunked-prefill admissions).
    def _decode(self, sampling):
        fn = self._decode_fns.get(sampling)
        if fn is not None:
            return fn
        model = self.model
        sampler = _row_sampler(*sampling)

        def decode(state, toks, pools, page_table, lengths, caps, keys):
            overrides = {k: Tensor(v, stop_gradient=True) for k, v in state.items()}
            lengths_e = jnp.minimum(lengths, caps)
            pkvs = [PagedLayerCache(kp, vp, page_table, lengths_e)
                    for kp, vp in pools]
            logits, presents = model.functional_call(
                overrides, Tensor(toks),
                position_ids=Tensor(lengths_e[:, None].astype(jnp.int32)),
                past_key_values=pkvs, use_cache=True, training=False,
            )
            nxt = sampler(logits._data[:, -1], keys).astype(jnp.int32)
            return nxt, tuple(
                (p.k_pages, p.v_pages) for p in presents
            )

        # donate the pools: a single-token decode must UPDATE the pool in
        # place, not copy it — without donation every step pays a full-pool
        # memcpy and doubles peak memory, against the engine's whole point
        fn = self._decode_fns[sampling] = _compilemem.ledgered_jit(
            decode, key=f"serve.decode[s{sampling}]", donate_argnums=(2,))
        _compilemem.ledger.note_cache_size("serve.decode",
                                           len(self._decode_fns))
        return fn

    def _decode_block_fn(self, sampling, k):
        """k decode steps fused into one dispatch: lax.scan over the
        single-step decode body, carrying (tokens, pools, lengths). Returns
        the [k, max_seqs] token block + the updated pools."""
        fn = self._decode_block_fns.get((sampling, k))
        if fn is not None:
            return fn
        model = self.model
        sampler = _row_sampler(*sampling)

        def decode_block(state, toks, pools, page_table, lengths, caps, keys):
            overrides = {kk: Tensor(v, stop_gradient=True) for kk, v in state.items()}

            def body(carry, step_keys):
                toks_c, pools_c, lengths_c = carry
                # freeze an over-budget row at its last reserved position
                # (identity for rows within budget — see caps note above)
                lengths_e = jnp.minimum(lengths_c, caps)
                pkvs = [PagedLayerCache(kp, vp, page_table, lengths_e)
                        for kp, vp in pools_c]
                logits, presents = model.functional_call(
                    overrides, Tensor(toks_c),
                    position_ids=Tensor(lengths_e[:, None].astype(jnp.int32)),
                    past_key_values=pkvs, use_cache=True, training=False,
                )
                nxt = sampler(logits._data[:, -1], step_keys).astype(jnp.int32)
                new_pools = tuple((p.k_pages, p.v_pages) for p in presents)
                return (nxt[:, None], new_pools, lengths_e + 1), nxt

            (_, pools_out, _), toks_block = jax.lax.scan(
                body, (toks, tuple(pools), lengths), keys)
            return toks_block, pools_out

        fn = self._decode_block_fns[(sampling, k)] = _compilemem.ledgered_jit(
            decode_block, key=f"serve.decode_block[k{k},s{sampling}]",
            donate_argnums=(2,))
        _compilemem.ledger.note_cache_size("serve.decode_block",
                                           len(self._decode_block_fns))
        return fn

    # ---- per-request LoRA programs (ISSUE 19) -----------------------------
    # An adapter is a low-rank update to the LM-HEAD projection:
    #
    #     logits = base_head(h) + scale * (h @ A) @ B
    #
    # with A [hidden, r] / B [r, vocab] float32. The lora program variants
    # run the INNER transformer (model.llama) through functional_call —
    # exactly the ops the base programs run — then apply the same-ops base
    # head plus the gathered per-row delta. The compile-time constants are
    # (sampling, rank, block k); adapter WEIGHTS are runtime operands
    # (decode: fixed-depth [_lora_slots+1, ...] stacks indexed per row,
    # slot 0 all-zeros), so hot-swapping adapters within a warmed
    # (rank, sampling) signature never recompiles. A batch with no
    # adapters at all never enters these programs: the base path stays
    # byte-for-byte the pre-LoRA engine.

    @staticmethod
    def _lora_inner_overrides(state):
        """Full-model raw state -> inner-model functional_call overrides
        ("llama."-prefix keys stripped; the head weight stays behind for
        the explicit base-head matmul below)."""
        return {k[len("llama."):]: Tensor(v, stop_gradient=True)
                for k, v in state.items() if k.startswith("llama.")}

    @staticmethod
    def _lora_base_head(h, state, tied):
        """The base LM-head projection with the SAME ops the model's own
        forward uses (F.linear / matmul(transpose_y=True)) — a zero-delta
        lora row must sample the bit-identical token the base program
        would have."""
        if tied:
            return h @ jnp.swapaxes(state["llama.embed_tokens.weight"],
                                    -1, -2)
        return h @ state["lm_head.weight"]

    def _lora_prefill(self, bucket, sampling, rank):
        """Monolithic prefill + adapter head for the request's OWN A/B
        (per-request operands — prefill is one request wide, no stacking
        needed). Same return contract as _prefill."""
        key3 = (bucket, sampling, rank)
        fn = self._lora_prefill_fns.get(key3)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)

        def prefill(state, ids_p, true_len, key, a_w, b_w, scale):
            overrides = self._lora_inner_overrides(state)
            caches = model.init_cache(1, bucket)
            wrapped = [(Tensor(kc), Tensor(vc)) for kc, vc in caches]
            h, presents = inner.functional_call(
                overrides, Tensor(ids_p), past_key_values=wrapped,
                cache_position=Tensor(jnp.int32(0)), use_cache=True,
                training=False,
            )
            h_last = jax.lax.dynamic_index_in_dim(h._data, true_len - 1,
                                                  axis=1, keepdims=False)
            base = self._lora_base_head(h_last, state, tied)  # [1, V]
            delta = ((h_last.astype(jnp.float32) @ a_w) @ b_w) * scale
            tok0 = sampler(base + delta, key[None])[0].astype(jnp.int32)
            ks = jnp.stack([p[0]._data[0] for p in presents])
            vs = jnp.stack([p[1]._data[0] for p in presents])
            return tok0, ks, vs

        fn = self._lora_prefill_fns[key3] = _compilemem.ledgered_jit(
            prefill, key=f"serve.lora_prefill[r{rank},b{bucket},s{sampling}]")
        _compilemem.ledger.note_cache_size("serve.lora_prefill",
                                           len(self._lora_prefill_fns))
        return fn

    def _lora_prefill_suffix(self, n_prefix_pages, suffix_bucket, sampling,
                             rank):
        """Prefix-cache-hit suffix prefill + adapter head. Prefix KV is
        HEAD-independent (the adapter only touches logits), so adapter
        requests share cached prompt pages with everyone else."""
        key4 = (n_prefix_pages, suffix_bucket, sampling, rank)
        fn = self._lora_suffix_fns.get(key4)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)
        plen = n_prefix_pages * self.page_size

        def prefill_suf(state, ks_pre, vs_pre, ids_suf, suf_len, key,
                        a_w, b_w, scale):
            overrides = self._lora_inner_overrides(state)
            caches = model.init_cache(1, plen + suffix_bucket)
            wrapped = []
            for l, (kc, vc) in enumerate(caches):
                kc = kc.at[0, :plen].set(ks_pre[l].astype(kc.dtype))
                vc = vc.at[0, :plen].set(vs_pre[l].astype(vc.dtype))
                wrapped.append((Tensor(kc), Tensor(vc)))
            h, presents = inner.functional_call(
                overrides, Tensor(ids_suf), past_key_values=wrapped,
                cache_position=Tensor(jnp.int32(plen)), use_cache=True,
                training=False,
            )
            h_last = jax.lax.dynamic_index_in_dim(h._data, suf_len - 1,
                                                  axis=1, keepdims=False)
            base = self._lora_base_head(h_last, state, tied)
            delta = ((h_last.astype(jnp.float32) @ a_w) @ b_w) * scale
            tok0 = sampler(base + delta, key[None])[0].astype(jnp.int32)
            ks = jnp.stack([p[0]._data[0, plen:] for p in presents])
            vs = jnp.stack([p[1]._data[0, plen:] for p in presents])
            return tok0, ks, vs

        fn = self._lora_suffix_fns[key4] = _compilemem.ledgered_jit(
            prefill_suf,
            key=f"serve.lora_suffix[r{rank},p{n_prefix_pages},"
                f"b{suffix_bucket},s{sampling}]")
        _compilemem.ledger.note_cache_size("serve.lora_suffix",
                                           len(self._lora_suffix_fns))
        return fn

    def _lora_decode(self, sampling, rank):
        """Single-step batched multi-adapter decode: per-row indices
        gather each slot's A/B/scale from the fixed-depth stacks inside
        the program. Row 0 of the stacks is zeros — no-adapter co-tenants
        add an exact 0.0 delta and sample the base token bit-for-bit."""
        key2 = (sampling, rank)
        fn = self._lora_decode_fns.get(key2)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)

        def decode(state, toks, pools, page_table, lengths, caps, keys,
                   a_stack, b_stack, scales, lora_idx):
            overrides = self._lora_inner_overrides(state)
            lengths_e = jnp.minimum(lengths, caps)
            pkvs = [PagedLayerCache(kp, vp, page_table, lengths_e)
                    for kp, vp in pools]
            h, presents = inner.functional_call(
                overrides, Tensor(toks),
                position_ids=Tensor(lengths_e[:, None].astype(jnp.int32)),
                past_key_values=pkvs, use_cache=True, training=False,
            )
            hd = h._data                       # [max_seqs, 1, hidden]
            base = self._lora_base_head(hd, state, tied)
            a_rows = a_stack[lora_idx]         # [max_seqs, hidden, r]
            b_rows = b_stack[lora_idx]         # [max_seqs, r, vocab]
            delta = jnp.einsum("bsh,bhr->bsr", hd.astype(jnp.float32),
                               a_rows)
            delta = jnp.einsum("bsr,brv->bsv", delta, b_rows)
            logits = base + delta * scales[lora_idx][:, None, None]
            nxt = sampler(logits[:, -1], keys).astype(jnp.int32)
            return nxt, tuple((p.k_pages, p.v_pages) for p in presents)

        fn = self._lora_decode_fns[key2] = _compilemem.ledgered_jit(
            decode, key=f"serve.lora_decode[r{rank},s{sampling}]",
            donate_argnums=(2,))
        _compilemem.ledger.note_cache_size("serve.lora_decode",
                                           len(self._lora_decode_fns))
        return fn

    def _lora_block_fn(self, sampling, rank, k):
        """k lora decode steps fused into one dispatch — _decode_block_fn
        with the adapter gather applied per scan step (the gathered rows
        are loop-invariant, hoisted once outside the scan)."""
        key3 = (sampling, rank, k)
        fn = self._lora_block_fns.get(key3)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)

        def decode_block(state, toks, pools, page_table, lengths, caps,
                         keys, a_stack, b_stack, scales, lora_idx):
            overrides = self._lora_inner_overrides(state)
            a_rows = a_stack[lora_idx]
            b_rows = b_stack[lora_idx]
            s_rows = scales[lora_idx][:, None, None]

            def body(carry, step_keys):
                toks_c, pools_c, lengths_c = carry
                lengths_e = jnp.minimum(lengths_c, caps)
                pkvs = [PagedLayerCache(kp, vp, page_table, lengths_e)
                        for kp, vp in pools_c]
                h, presents = inner.functional_call(
                    overrides, Tensor(toks_c),
                    position_ids=Tensor(
                        lengths_e[:, None].astype(jnp.int32)),
                    past_key_values=pkvs, use_cache=True, training=False,
                )
                hd = h._data
                base = self._lora_base_head(hd, state, tied)
                delta = jnp.einsum("bsh,bhr->bsr",
                                   hd.astype(jnp.float32), a_rows)
                delta = jnp.einsum("bsr,brv->bsv", delta, b_rows)
                logits = base + delta * s_rows
                nxt = sampler(logits[:, -1], step_keys).astype(jnp.int32)
                new_pools = tuple((p.k_pages, p.v_pages) for p in presents)
                return (nxt[:, None], new_pools, lengths_e + 1), nxt

            (_, pools_out, _), toks_block = jax.lax.scan(
                body, (toks, tuple(pools), lengths), keys)
            return toks_block, pools_out

        fn = self._lora_block_fns[key3] = _compilemem.ledgered_jit(
            decode_block,
            key=f"serve.lora_decode_block[r{rank},k{k},s{sampling}]",
            donate_argnums=(2,))
        _compilemem.ledger.note_cache_size("serve.lora_decode_block",
                                           len(self._lora_block_fns))
        return fn

    # ---- ragged mixed programs (ISSUE 20) ---------------------------------
    # ONE program per (sampling, kv-dtype[, lora-rank]) replaces the whole
    # bucket ladder. The packed pass runs every prompt chunk and every
    # decode feed token in a single [T]-token forward through the ragged
    # paged cache (prompt length is a RUNTIME operand — cu_q_lens — not a
    # compile-time bucket), samples each participant's boundary token, then
    # scans the remaining k-1 fixed decode steps with the legacy block
    # body. Mid-prefill rows are excluded from the scan by construction:
    # their caps are 0 (write position frozen at 0) and their scan_table
    # row is all-zeros, so their scan writes land in the scratch page.

    def _ragged_fn(self, sampling):
        fn = self._ragged_fns.get(sampling)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)
        T = self._ragged_tokens
        k = self.decode_block

        def ragged_step(state, tok_block, cu, row_of, token_pos, valid,
                        use_last, last, pools, page_table, scan_table,
                        lengths, caps, keys):
            overrides = {kk: Tensor(v, stop_gradient=True)
                         for kk, v in state.items()}
            inner_ov = self._lora_inner_overrides(state)
            q_lens = cu[1:] - cu[:-1]
            # decode rows chained off an in-flight block feed on its device
            # `last` tokens; each row's feed token sits at its span start.
            # Rows with q_len == 0 alias position min(cu, T-1) — they write
            # back the value already there, so duplicates are harmless.
            first_idx = jnp.minimum(cu[:-1], T - 1)
            upd = jnp.where(use_last[:, 0], last[:, 0], tok_block[first_idx])
            toks_in = tok_block.at[first_idx].set(upd)
            kv_lens = lengths + q_lens  # POST-write totals (ragged contract)
            rcaches = [RaggedLayerCache(kp, vp, page_table, kv_lens, cu,
                                        row_of, token_pos, valid)
                       for kp, vp in pools]
            h, presents = inner.functional_call(
                inner_ov, Tensor(toks_in[None]),
                position_ids=Tensor(token_pos[None].astype(jnp.int32)),
                past_key_values=rcaches, use_cache=True, training=False,
            )
            # each participant samples from its LAST packed token (span end)
            b_idx = jnp.clip(cu[1:] - 1, 0, T - 1)
            h_b = h._data[0, b_idx]                         # [max_seqs, H]
            base = self._lora_base_head(h_b, state, tied)   # [max_seqs, V]
            tok0 = sampler(base, keys[0]).astype(jnp.int32)
            pools1 = tuple((p.k_pages, p.v_pages) for p in presents)

            def body(carry, step_keys):
                toks_c, pools_c, lengths_c = carry
                lengths_e = jnp.minimum(lengths_c, caps)
                pkvs = [PagedLayerCache(kp, vp, scan_table, lengths_e)
                        for kp, vp in pools_c]
                logits, presents2 = model.functional_call(
                    overrides, Tensor(toks_c),
                    position_ids=Tensor(lengths_e[:, None].astype(jnp.int32)),
                    past_key_values=pkvs, use_cache=True, training=False,
                )
                nxt = sampler(logits._data[:, -1], step_keys).astype(jnp.int32)
                new_pools = tuple((p.k_pages, p.v_pages) for p in presents2)
                return (nxt[:, None], new_pools, lengths_e + 1), nxt

            (_, pools_out, _), toks_tail = jax.lax.scan(
                body, (tok0[:, None], pools1, kv_lens), keys[1:])
            blk = jnp.concatenate([tok0[None], toks_tail], axis=0)
            return blk, pools_out

        fn = self._ragged_fns[sampling] = _compilemem.ledgered_jit(
            ragged_step, key=f"serve.ragged[k{k},s{sampling}]",
            donate_argnums=(8,))
        _compilemem.ledger.note_cache_size("serve.ragged",
                                           len(self._ragged_fns))
        return fn

    def _lora_ragged_fn(self, sampling, rank):
        """_ragged_fn with the fixed-depth adapter-stack gather on every
        head projection (packed boundary rows AND scan steps) — slot 0 of
        the stacks is zeros, so no-adapter rows add an exact +0.0 delta."""
        key2 = (sampling, rank)
        fn = self._lora_ragged_fns.get(key2)
        if fn is not None:
            return fn
        model = self.model
        inner = model.llama
        tied = model.lm_head is None
        sampler = _row_sampler(*sampling)
        T = self._ragged_tokens
        k = self.decode_block

        def ragged_step(state, tok_block, cu, row_of, token_pos, valid,
                        use_last, last, pools, page_table, scan_table,
                        lengths, caps, keys, a_stack, b_stack, scales,
                        lora_idx):
            inner_ov = self._lora_inner_overrides(state)
            a_rows = a_stack[lora_idx]
            b_rows = b_stack[lora_idx]
            s_rows = scales[lora_idx]
            q_lens = cu[1:] - cu[:-1]
            first_idx = jnp.minimum(cu[:-1], T - 1)
            upd = jnp.where(use_last[:, 0], last[:, 0], tok_block[first_idx])
            toks_in = tok_block.at[first_idx].set(upd)
            kv_lens = lengths + q_lens
            rcaches = [RaggedLayerCache(kp, vp, page_table, kv_lens, cu,
                                        row_of, token_pos, valid)
                       for kp, vp in pools]
            h, presents = inner.functional_call(
                inner_ov, Tensor(toks_in[None]),
                position_ids=Tensor(token_pos[None].astype(jnp.int32)),
                past_key_values=rcaches, use_cache=True, training=False,
            )
            b_idx = jnp.clip(cu[1:] - 1, 0, T - 1)
            h_b = h._data[0, b_idx]
            base = self._lora_base_head(h_b, state, tied)
            delta = jnp.einsum("bh,bhr->br", h_b.astype(jnp.float32), a_rows)
            delta = jnp.einsum("br,brv->bv", delta, b_rows)
            tok0 = sampler(base + delta * s_rows[:, None],
                           keys[0]).astype(jnp.int32)
            pools1 = tuple((p.k_pages, p.v_pages) for p in presents)
            s3 = s_rows[:, None, None]

            def body(carry, step_keys):
                toks_c, pools_c, lengths_c = carry
                lengths_e = jnp.minimum(lengths_c, caps)
                pkvs = [PagedLayerCache(kp, vp, scan_table, lengths_e)
                        for kp, vp in pools_c]
                h2, presents2 = inner.functional_call(
                    inner_ov, Tensor(toks_c),
                    position_ids=Tensor(lengths_e[:, None].astype(jnp.int32)),
                    past_key_values=pkvs, use_cache=True, training=False,
                )
                hd = h2._data
                base2 = self._lora_base_head(hd, state, tied)
                d2 = jnp.einsum("bsh,bhr->bsr", hd.astype(jnp.float32),
                                a_rows)
                d2 = jnp.einsum("bsr,brv->bsv", d2, b_rows)
                logits = base2 + d2 * s3
                nxt = sampler(logits[:, -1], step_keys).astype(jnp.int32)
                new_pools = tuple((p.k_pages, p.v_pages) for p in presents2)
                return (nxt[:, None], new_pools, lengths_e + 1), nxt

            (_, pools_out, _), toks_tail = jax.lax.scan(
                body, (tok0[:, None], pools1, kv_lens), keys[1:])
            blk = jnp.concatenate([tok0[None], toks_tail], axis=0)
            return blk, pools_out

        fn = self._lora_ragged_fns[key2] = _compilemem.ledgered_jit(
            ragged_step, key=f"serve.lora_ragged[r{rank},k{k},s{sampling}]",
            donate_argnums=(8,))
        _compilemem.ledger.note_cache_size("serve.lora_ragged",
                                           len(self._lora_ragged_fns))
        return fn

    # ---- LoRA weight residency --------------------------------------------
    def _lora_dev(self, adapter):
        """Host A/B -> device arrays, digest-keyed LRU (the hot working
        set transfers once; re-registration under a new digest is a new
        entry, so stale weights can never serve)."""
        ent = self._lora_device.get(adapter.digest)
        if ent is None:
            ent = (jnp.asarray(adapter.a), jnp.asarray(adapter.b))
            self._lora_device[adapter.digest] = ent
            while len(self._lora_device) > 32:
                self._lora_device.popitem(last=False)
        else:
            self._lora_device.move_to_end(adapter.digest)
        return ent

    def _lora_stack(self, rank, adapters):
        """(a_stack, b_stack, scales, digest->index) for a digest-sorted
        working set. Depth is FIXED at ``_lora_slots + 1`` (slot 0 =
        zeros for no-adapter rows; tail slots zero-padded) so the decode
        signature never varies with the working set — the zero-warm-
        recompile contract. Keyed by (rank, digests), LRU-bounded."""
        digs = tuple(ad.digest for ad in adapters)
        cached = self._lora_stack_cache.get((rank, digs))
        if cached is None:
            hidden, vocab = self._lora_dims
            za = jnp.zeros((hidden, rank), jnp.float32)
            zb = jnp.zeros((rank, vocab), jnp.float32)
            a_list, b_list, s_list = [za], [zb], [0.0]
            for ad in adapters:
                a_dev, b_dev = self._lora_dev(ad)
                a_list.append(a_dev)
                b_list.append(b_dev)
                s_list.append(float(ad.scale))
            while len(a_list) < self._lora_slots + 1:
                a_list.append(za)
                b_list.append(zb)
                s_list.append(0.0)
            cached = (jnp.stack(a_list), jnp.stack(b_list),
                      jnp.asarray(s_list, jnp.float32))
            self._lora_stack_cache[(rank, digs)] = cached
            while len(self._lora_stack_cache) > 8:
                self._lora_stack_cache.popitem(last=False)
        else:
            self._lora_stack_cache.move_to_end((rank, digs))
        return cached + ({d: i + 1 for i, d in enumerate(digs)},)

    def _lora_reject(self, ad):
        """Why this adapter can never run on this engine (None = it can):
        admission fails the request alone instead of deferring forever."""
        hidden, vocab = self._lora_dims
        if not hasattr(self.model, "llama") or hidden is None \
                or vocab is None:
            return ValueError(
                "LoRA adapters need a LlamaForCausalLM-shaped model "
                "(inner .llama + hidden_size/vocab_size config)")
        if ad.a.shape[0] != hidden or ad.b.shape[1] != vocab:
            return ValueError(
                f"adapter {ad.name!r} shapes {ad.a.shape}/{ad.b.shape} "
                f"do not match model hidden={hidden} vocab={vocab}")
        return None

    def warmup(self, prompt_lens=None, do_sample=False, temperature=1.0,
               top_k=0, top_p=1.0, shared_prefix_lens=(), buckets=None,
               sampling=None, lora_ranks=()):
        """Compile every program serve() can hit for prompts of these
        lengths BEFORE latency-sensitive serving (reference:
        AnalysisPredictor warmup / TRT engine build-ahead): one dummy
        request per prompt bucket (prefill + page-insert programs — under
        ``prefill_chunk`` the dummy serves walk the chunk ladder instead,
        which is exactly the program set real traffic will hit), and one
        serve of 2*decode_block-1 tokens whose shrinking tail walks every
        power-of-two block-decode program (k = decode_block, ..., 2, 1).
        Found on real TPU: without this, the k=32/16/8 block programs
        compile through the remote-compile tunnel inside the serving loop —
        ~1.5 s/compile dwarfing the ~80 ms dispatch they fuse.

        ``buckets`` is an alias for ``prompt_lens`` (the AOT-precompile
        vocabulary the serving frontend uses at replica start).
        ``sampling`` precompiles for a LIST of sampling configs in one
        call — each entry is a ``(do_sample, temperature, top_k, top_p)``
        tuple (or a single tuple) — since the sampler is a compile-time
        constant of every prefill/decode program. Wall time lands in the
        ``serve.compile_warmup_s`` histogram.

        ``lora_ranks`` (ISSUE 19) additionally compiles the per-request
        LoRA program set for each adapter rank — lora prefill per prompt
        bucket plus the lora decode/block ladder — by serving a
        zero-weight adapter of that rank (adapter weights are runtime
        operands, so warming any adapter warms them all for the rank).
        The prefix-cache lora_suffix programs compile on first hit."""
        if buckets is not None:
            prompt_lens = buckets
        if prompt_lens is None:
            raise ValueError("warmup() needs prompt_lens= or buckets=")
        if sampling is None:
            configs = [(do_sample, temperature, top_k, top_p)]
        elif sampling and not isinstance(sampling[0], (tuple, list)):
            configs = [tuple(sampling)]
        else:
            configs = [tuple(s) for s in sampling]
        t_warm0 = time.monotonic()
        try:
            # ledger trigger scope (ISSUE 8): compiles inside warmup are
            # deliberate AOT work, not cold-path stalls — /compilez and
            # the bench contract separate them by this label
            with _compilemem.ledger.trigger("warmup"):
                for cfg in configs:
                    self._warmup_one(prompt_lens, shared_prefix_lens, *cfg)
                for rank in lora_ranks:
                    for cfg in configs:
                        self._warmup_lora(prompt_lens, int(rank), *cfg)
        finally:
            _M_WARMUP.observe(time.monotonic() - t_warm0)

    def _warmup_one(self, prompt_lens, shared_prefix_lens, do_sample,
                    temperature, top_k, top_p):
        kw = dict(do_sample=do_sample, temperature=temperature,
                  top_k=top_k, top_p=top_p)
        stats_before = dict(self.stats)  # warmup must not pollute diagnostics
        # bypass the prefix cache during the dummy serves: the all-ones
        # prompts would cross-hit each other, compiling suffix programs
        # INSTEAD of the full-prefill programs real cache-miss requests need
        # (the exact mid-serve compile stall warmup exists to prevent) and
        # leaving junk ones-pages indexed
        pfx, self.enable_prefix_cache = self.enable_prefix_cache, False
        try:
            self._warmup_serves(prompt_lens, kw)
        finally:
            self.enable_prefix_cache = pfx  # lint: shared-mutation-without-lock-ok (engine fields are dispatcher-owned — single-threaded by contract)
            self.stats = stats_before  # lint: shared-mutation-without-lock-ok (same dispatcher-owned contract)
        if pfx and shared_prefix_lens:
            # compile the cache-HIT programs too: for each expected shared
            # prefix length, the page gather + suffix prefill a matching
            # request will dispatch. Pure dummy calls — no cache state or
            # pool contents are touched (gather reads, prefill returns).
            sampling = ((False, 1.0, 0, 1.0) if not do_sample else
                        (True, float(temperature), int(top_k), float(top_p)))
            with _COMPILE_LOCK:  # no tracer capture while a sibling traces
                state = self.model.raw_state_dict()
            bs = self.page_size
            for sp in shared_prefix_lens:
                for l in prompt_lens:
                    if l <= sp:
                        continue
                    n_pre = min(int(sp) // bs, (int(l) - 1) // bs)
                    while n_pre:
                        suffix_len = int(l) - n_pre * bs
                        if self.prefill_chunk \
                                and suffix_len > self.prefill_chunk:
                            region = self._chunk_plan(suffix_len)[2]
                        else:
                            region = self._pages_for_bucket(
                                prompt_bucket(suffix_len), bs)
                        if n_pre + region <= self.pages_per_seq:
                            break
                        n_pre -= 1
                    if not n_pre:
                        continue
                    # the programs a HIT request will actually dispatch:
                    # under chunking that is the chunk ladder shifted by
                    # the hit width (gather+suffix at filled = n_pre,
                    # n_pre + chunk_pages, ...), NOT the monolithic
                    # cache-hit suffix program — warming the wrong one
                    # leaves the real ladder to compile mid-serve
                    suffix_len = int(l) - n_pre * bs
                    if self.prefill_chunk \
                            and suffix_len > self.prefill_chunk:
                        n_full, flen, _ = self._chunk_plan(suffix_len)
                        cpg = self.prefill_chunk // bs
                        stages = [(n_pre + j * cpg, self.prefill_chunk)
                                  for j in range(n_full)]
                        stages.append((n_pre + n_full * cpg,
                                       prompt_bucket(flen)))
                    else:
                        stages = [(n_pre, prompt_bucket(suffix_len))]
                    for filled, cbucket in stages:
                        with self._locked_dispatch(
                                ("gather", filled),
                                ("suffix", filled, cbucket, sampling),
                                ("insert", cbucket)):
                            ks, vs = self._gather_prefix(filled)(
                                tuple(self.pools),
                                jnp.zeros((filled,), jnp.int32))  # scratch
                            _, cks, cvs = self._prefill_suffix(
                                filled, cbucket, sampling)(
                                state, ks, vs,
                                jnp.zeros((1, cbucket), jnp.int32),
                                jnp.int32(1), jax.random.PRNGKey(0))
                            # dummy insert aimed at page 0: scratch absorbs
                            # the writes, and the hit path's insert program
                            # for this chunk shape is now warm too
                            npg = self._pages_for_bucket(cbucket, bs)
                            self.pools = list(self._insert(cbucket)(
                                tuple(self.pools), cks, cvs,
                                jnp.zeros((npg,), jnp.int32)))

    def _warmup_serves(self, prompt_lens, kw):
        if self._ragged:
            # ragged mode (ISSUE 20): prompt length is a RUNTIME operand of
            # the mixed program, so the whole bucket/chunk ladder collapses
            # to ONE dummy serve per sampling config. max_new=decode_block+1
            # touches the mixed program (graduation step) AND the fixed-k
            # decode-only block (the following step) — the full program set
            # steady-state traffic dispatches; sampled configs build the
            # key program inside those dispatches.
            fit = min(self.max_len - 1,
                      self._available_pages() * self.page_size - 1)
            n = max(min(self.decode_block + 1, fit), 1)
            self.serve([np.ones(1, np.int32)], max_new_tokens=n, **kw)
            return
        # Decode-program ladder on a length-1 dummy prompt: the decode/block
        # programs don't depend on prompt length, and the shortest prompt
        # maximizes the admissible walk under both the max_len check and the
        # page pool (tight pools are the engine's documented configuration).
        # max_new=walk: remaining after the prefill token is walk-1 = 2k-2,
        # so the loop's shrinking k visits decode_block, ..., 4, 2 exactly
        # once each; max_new=2 leaves remaining=1 and compiles the k=1
        # (plain per-token decode) program, which the even walk never hits.
        ladder_bucket = prompt_bucket(1)
        fit = min(self.max_len - 1,
                  self._available_pages() * self.page_size - ladder_bucket)
        runs = [2]  # k=1 (plain per-token decode) program
        if self.decode_block > 1:
            runs.append(2 * self.decode_block - 1)  # k = decode_block..2
        # cap to what the pool/max_len admit: a capped walk still compiles
        # every block program a same-pool serve can reach (k is bounded by
        # the shrinking `remaining` either way)
        runs = sorted({min(n, fit) for n in runs if fit >= 2})
        for n in runs:
            self.serve([np.ones(1, np.int32)], max_new_tokens=n, **kw)
        # Prefill + page-insert programs: one representative REAL length per
        # PROGRAM SIGNATURE (a prompt of the bucket length itself may not be
        # servable when the bucket touches max_len). Monolithic prompts
        # share programs per bucket; chunked prompts share them per
        # (full-chunk count, final-chunk bucket) — two prompts in the same
        # bucket can walk different chunk ladders, and a ladder left cold
        # here compiles inside the latency-sensitive serve instead.
        rep = {}
        for l in prompt_lens:
            l = int(l)
            if self.prefill_chunk and l > self.prefill_chunk:
                n_full, flen, _ = self._chunk_plan(l)
                key = ("chunk", n_full, prompt_bucket(flen))
            else:
                key = ("mono", prompt_bucket(l))
            rep[key] = min(rep.get(key, l), l)
        for key in sorted(rep, key=str):
            if key == ("mono", ladder_bucket) and runs:
                continue  # the ladder serves above already compiled it
            self.serve([np.ones(rep[key], np.int32)], max_new_tokens=1, **kw)

    def _warmup_lora(self, prompt_lens, rank, do_sample, temperature,
                     top_k, top_p):
        """Compile the rank's lora program set with a zero-weight dummy
        adapter (delta == 0, so the dummy serves stay as harmless as the
        base warmup's). Adapter requests always prefill monolithically,
        so the bucket walk is mono-only regardless of prefill_chunk."""
        from ..serving.adapters import LoRAAdapter

        hidden, vocab = self._lora_dims
        ad = LoRAAdapter(f"warmup-r{rank}",
                         np.zeros((hidden, rank), np.float32),
                         np.zeros((rank, vocab), np.float32))
        kw = dict(do_sample=do_sample, temperature=temperature,
                  top_k=top_k, top_p=top_p, adapters=ad)
        stats_before = dict(self.stats)
        pfx, self.enable_prefix_cache = self.enable_prefix_cache, False
        try:
            if self._ragged:
                # one dummy serve per rank covers the mixed lora program +
                # the fixed-k lora block (same collapse as _warmup_serves)
                fit = min(self.max_len - 1,
                          self._available_pages() * self.page_size - 1)
                n = max(min(self.decode_block + 1, fit), 1)
                self.serve([np.ones(1, np.int32)], max_new_tokens=n, **kw)
                return
            ladder_bucket = prompt_bucket(1)
            fit = min(self.max_len - 1,
                      self._available_pages() * self.page_size
                      - ladder_bucket)
            runs = [2]
            if self.decode_block > 1:
                runs.append(2 * self.decode_block - 1)
            runs = sorted({min(n, fit) for n in runs if fit >= 2})
            for n in runs:
                self.serve([np.ones(1, np.int32)], max_new_tokens=n, **kw)
            rep = {}
            for l in prompt_lens:
                b = prompt_bucket(int(l))
                rep[b] = min(rep.get(b, int(l)), int(l))
            for b in sorted(rep):
                if b == ladder_bucket and runs:
                    continue
                self.serve([np.ones(rep[b], np.int32)],
                           max_new_tokens=1, **kw)
        finally:
            self.enable_prefix_cache = pfx  # lint: shared-mutation-without-lock-ok (engine fields are dispatcher-owned — single-threaded by contract)
            self.stats = stats_before  # lint: shared-mutation-without-lock-ok (same dispatcher-owned contract)

    # ---- scheduler --------------------------------------------------------
    def pool_bytes(self):
        import jax

        return sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(self.pools))

    #: bounded retry for the decode dispatch: a transient dispatch failure
    #: (injected outage, flaky transport to a remote backend) retries the
    #: whole batch step; deterministic compile/shape errors are not
    #: ConnectionErrors and still raise immediately.
    retry_policy = RetryPolicy(attempts=3, base_delay=0.05)


    # ---- online request lifecycle -----------------------------------------
    # The serving control plane (paddle_tpu/serving) drives the engine with
    # these three hooks from a per-replica dispatcher thread:
    #
    #   try_admit_one(req)  non-blocking admission of ONE EngineRequest
    #   step()              one fused decode dispatch; returns finished reqs
    #   drain()             finish everything admitted, admit nothing
    #
    # serve() below is rebuilt ON TOP of the same hooks, so the batch path
    # and the online path cannot drift. The engine is single-threaded by
    # contract: all three hooks must be called from one thread (the
    # dispatcher); the only cross-thread writes it tolerates are the
    # EngineRequest.cancelled flags, honored at block boundaries.

    def idle(self):
        return (not self._active and not self._prefilling
                and self._inflight is None and not self._pending_retired)

    def active_count(self):
        # mid-chunked-prefill requests occupy slots too — the router's
        # load signal must see them
        return len(self._active) + len(self._prefilling)

    def has_free_slot(self):
        return bool(self.free_slots)

    def active_prefills(self):
        """Mid-chunked-prefill slot count — the brownout ladder's
        ``shed_prefill_depth`` rung caps this before shedding requests,
        and the frontend's role-aware pressure split reads it."""
        return len(self._prefilling)

    # ---- disaggregated prefill/decode handoff hooks (ISSUE 16) ------------
    # A prefill-role replica produces a request's first tokens, then the
    # frontend exports its KV pages, publishes a handoff bundle
    # (serving/handoff.py), detaches the request WITHOUT finishing its
    # handle, and a decode-role replica adopts the pages into its own pool
    # and continues bit-identically. All three hooks run on the owning
    # dispatcher thread (the engine's single-threaded contract).

    def _settle_inflight(self):
        """Read back the in-flight decode block NOW (instead of at the next
        step()) so every active request's emitted tokens equal its
        dispatched tokens — the consistency an exported bundle needs.
        Requests that retire during the readback are queued for the next
        step() to return, so the frontend still sees them finish."""
        rec = self._inflight
        if rec is not None:
            self._inflight = None
            self._pending_retired.extend(self._process_block(rec))

    def export_pages(self, slot):
        """Gather ``slot``'s KV pages to the host for a handoff bundle:
        ``{"n_pages", "ks", "vs"}`` with dense ``[L, n*bs, Hkv, D]``
        arrays (the prefix-cache gather, reused — float pools only; int8
        export raises and the caller degrades to blended). Returns None
        when the request finished while the in-flight block settled —
        nothing left to hand off. Prefill-side only: the host sync here is
        deliberate and NOT part of any decode critical section."""
        self._settle_inflight()
        req = self._active.get(slot)
        if req is None or req.finished:
            return None
        n = len(req.pages)
        ks, vs = self._gather_prefix(n)(
            tuple(self.pools), jnp.asarray(req.pages, jnp.int32))
        return {"n_pages": n, "ks": np.asarray(ks), "vs": np.asarray(vs)}

    def detach_request(self, slot):
        """Release ``slot`` WITHOUT finishing the request's handle: the
        request now lives in its published bundle and the adopting decode
        replica continues it. Frees the slot and pages exactly like
        _retire but leaves the EngineRequest unfinished (tokens,
        dispatch count, and key stream intact for the adopter). Call only
        after export_pages() in the same dispatcher turn — no step() may
        run in between, or the detached bundle goes stale."""
        req = self._active.pop(slot)
        self._unref_pages(req.pages)
        self.free_slots.append(slot)
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        req.pages = []
        req.slot = None
        self._slot_adapter.pop(slot, None)
        if not self._active and not self._prefilling:
            self._active_sampling = None
            self._active_lora_rank = None
        return req

    def adopt_request(self, req, payloads):
        """Admission twin for a handed-off request: scatter its exported
        page payloads into this pool and register it mid-decode. ``req``
        already carries the bundle's validated continuation state (tokens,
        n_dispatched, last_token). Returns "admitted" / "deferred" /
        "failed" with try_admit_one's exact semantics. Restores the decode
        invariant ``lengths[slot] = len(prompt) + n_dispatched - 1`` so
        the next decode block's positions — and with the replayed key
        stream, its tokens — are bit-identical to never having moved.
        Adopted pages are private (never prefix-indexed): their digests
        were validated against the bundle, not against this pool's index."""
        if not self.free_slots:
            return "deferred"
        if (self._active or self._prefilling) \
                and self._active_sampling != req.sampling:
            return "deferred"
        n = int(payloads["n_pages"])
        if n > self.pages_per_seq:
            self._fail_request(req, ValueError(
                f"request {req.rid}: handoff bundle spans {n} pages, "
                f"page table holds {self.pages_per_seq}"))
            return "failed"
        if n > self._available_pages():
            if not self._active and not self._prefilling:
                self._fail_request(req, RuntimeError(
                    f"request {req.rid}: handoff bundle needs {n} pages, "
                    f"idle pool has {self._available_pages()}"))
                return "failed"
            self.stats["deferred_admissions"] += 1
            return "deferred"
        slot = self.free_slots.pop()
        pages = self._alloc_pages(n)
        self._ref_pages(pages)
        self.stats["peak_pages"] = max(self.stats["peak_pages"],
                                       self._pages_in_use)
        bucket = n * self.page_size
        try:
            with self._locked_dispatch(("insert", bucket)), \
                    _trace.span("serve.adopt"), self._xprof_annotation(req):
                chaos.site("serve.prefill")
                self.pools = list(self._insert(bucket)(
                    tuple(self.pools), jnp.asarray(payloads["ks"]),
                    jnp.asarray(payloads["vs"]),
                    jnp.asarray(pages, jnp.int32)))
        except Exception as e:  # fail THIS request alone, free everything
            self._unref_pages(pages)
            self.free_slots.append(slot)
            self._fail_request(req, e)
            return "failed"
        if req.sampling[0] and req.key_base is None:
            # same (seed, rid)-only stream root the prefill side used — an
            # 8-byte pull at adoption time, before any decode dispatch
            req.key_base = np.asarray(jax.random.fold_in(  # serve-readback-ok
                jax.random.PRNGKey(req.seed), req.rid))
        row = np.zeros(self.pages_per_seq, np.int32)
        row[:n] = pages
        self.page_table[slot] = row
        self.lengths[slot] = len(req.prompt) + req.n_dispatched - 1
        req.pages = pages
        req.slot = slot
        if req.t_admit is None:
            req.t_admit = time.monotonic()
        self._active[slot] = req
        self._active_sampling = req.sampling
        self._update_gauges()
        return "admitted"

    def _refresh_cache_guard(self, state):
        """Cached prefix KV is only valid under the weights it was computed
        with. Two-factor guard:
        - core.tensor_mutation_version: bumped by every set_value/load path
          AND the optimizer/train-step direct-rebind epilogues. A counter can
          never false-match when CPython recycles a freed array's address
          (the id()-only guard's failure mode, ADVICE r5 medium).
        - the id tuple: belt-and-braces for any future code that rebinds
          p._data without bumping — a rebind only slips through if EVERY new
          array also lands on its old address."""
        version = (_core.tensor_mutation_version(),
                   tuple(id(v) for v in state.values()))
        if version != self._cache_weights_version:
            if self._cache_weights_version is not None:
                self.clear_prefix_cache()
            self._cache_weights_version = version

    def _fail_request(self, req, exc):
        req.error = exc
        req.result = None
        req.finished = True
        req.t_done = time.monotonic()
        self.request_errors[req.rid] = exc
        # online mode: bounded map. serve() raises the bound to its batch
        # size for the duration — its docstring promises EVERY failed rid
        # an entry, and a >1024-request batch must not silently evict its
        # own early failures.
        while len(self.request_errors) > self._request_errors_bound:
            self.request_errors.pop(next(iter(self.request_errors)))
        self.stats["failed_requests"] += 1
        counters.bump("fault.serve.request_failed")

    def _retire(self, slot):
        req = self._active.pop(slot)
        req.result = np.asarray(req.tokens, np.int32)
        req.finished = True
        req.t_done = time.monotonic()
        self._unref_pages(req.pages)
        self.free_slots.append(slot)
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        self._slot_adapter.pop(slot, None)
        if not self._active and not self._prefilling:
            self._active_sampling = None
            self._active_lora_rank = None
        return req

    def _abort_prefill(self, slot, timed_out=False):
        """Cancelled/timed-out mid-chunked-prefill: drop the remaining
        chunks and retire with the prompt-only partial result (no token
        was ever produced for it)."""
        st = self._prefilling.pop(slot)
        req = st.req
        req.result = np.asarray(req.tokens, np.int32)
        req.finished = True
        req.timed_out = timed_out
        req.t_done = time.monotonic()
        self._unref_pages(st.pages)
        self.free_slots.append(slot)
        # mid-prefill slots keep their row at scratch by invariant, but
        # reset defensively like _retire does — a future progressive-row
        # install must not leak a stale row to the next tenant through
        # this path
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        self._slot_adapter.pop(slot, None)
        if not self._active and not self._prefilling:
            self._active_sampling = None
            self._active_lora_rank = None
        return req

    def _update_gauges(self):
        _M_OCCUPANCY.set(self.active_count() / self.max_seqs)
        free, evict = len(self.free_pages), len(self._evictable)
        _M_POOL_FREE.set(free)
        _M_POOL_EVICT.set(evict)
        _M_POOL_USED.set(self._pages_in_use)
        _M_POOL_FRAG.set(evict / (free + evict) if free + evict else 0.0)

    def try_admit_one(self, req):
        """Non-blocking admission of one :class:`EngineRequest`: page
        reservation + bucketed prefill + pool insert. Returns

        - ``"admitted"``  — prefilled into a slot; drive it with step()
        - ``"done"``      — admitted AND retired (eos/max_new on the first
                            token); ``req.result`` is set
        - ``"failed"``    — terminally failed in isolation (``req.error``)
        - ``"deferred"``  — try again later: no free slot, the running
                            group's sampling differs, or the pool is busy

        The caller owns the queue: pop the request on every status except
        ``"deferred"``. A deferred request on an IDLE engine never happens —
        a request the idle pool still cannot fit fails as impossible instead
        (the degradation contract's "fail alone, never wedge the queue")."""
        if not self.free_slots:
            return "deferred"
        ad = req.adapter
        if self._active or self._prefilling:
            if self._active_sampling != req.sampling:
                # the sampler is a compile-time constant of the decode
                # program: only requests sharing a sampling tuple can
                # co-schedule (a mid-prefill request will join the decode
                # group too)
                return "deferred"
            if ad is not None:
                if self._active_lora_rank is None:
                    # base group running: its decode program has no adapter
                    # plane, and converting mid-group would move plain
                    # co-tenants off the byte-identical base path — wait
                    return "deferred"
                if ad.rank != self._active_lora_rank:
                    # rank is a compile-time constant of the lora programs
                    return "deferred"
                digs = {a.digest for a in self._slot_adapter.values()}
                if ad.digest not in digs and len(digs) >= self._lora_slots:
                    # stacked-weights working set full (PADDLE_LORA_SLOTS)
                    return "deferred"
        # past the deferral gates the request is popped by the caller on
        # every return below, so this counts each request exactly once —
        # on BOTH the batch serve() path and the frontend's online path
        _M_REQUESTS.inc()
        # request-scoped trace (ISSUE 7): the admission span nests under
        # the frontend's attempt span; every return below closes it
        adm = req.trace.child("admit") if req.trace is not None else None
        prompt = req.prompt
        true_len = len(prompt)
        bucket = prompt_bucket(true_len)
        if true_len + req.max_new_tokens > self.max_len or bucket > self.max_len:
            # invalid request — reject IT, not the whole batch
            self._fail_request(req, ValueError(
                f"request {req.rid}: len {true_len} (bucket {bucket}) + "
                f"{req.max_new_tokens} exceeds max_len={self.max_len}"))
            if adm is not None:
                adm.end("error", error=req.error_message)
            return "failed"
        if ad is not None:
            err = self._lora_reject(ad)
            if err is not None:
                # wrong-model/wrong-shape adapter can NEVER run here —
                # fail it alone instead of deferring forever
                self._fail_request(req, err)
                if adm is not None:
                    adm.end("error", error=req.error_message)
                return "failed"
        # reuse the version-checked capture across admissions AND decode
        # steps — the O(n_params) tree walk stays off the TTFT-critical path
        state = self._captured_state()
        bs_ = self.page_size
        if self.enable_prefix_cache:
            self._refresh_cache_guard(state)
            n_pre, shared, digests = self._match_prefix(prompt, true_len)
        else:
            n_pre, shared, digests = 0, [], None

        def _region_for(suffix_len):
            # pages the PREFILL writes: the chunk ladder's exact page
            # counts under chunking, the bucket-rounded region otherwise.
            # Ragged prefill (ISSUE 20) writes token-exact — no bucket
            # rounding, so reservations shrink to the true footprint.
            if self._ragged:
                return -(-suffix_len // bs_)
            if self.prefill_chunk and suffix_len > self.prefill_chunk:
                return self._chunk_plan(suffix_len)[2]
            return self._pages_for_bucket(prompt_bucket(suffix_len), bs_)

        # shrink the hit until prefix + the prefill region fit the page-
        # table row: the suffix bucket rounds up independently, so a
        # full-width hit can otherwise need pages_per_seq+1 pages
        suffix_len = true_len
        while n_pre:
            suffix_len = true_len - n_pre * bs_
            if n_pre + _region_for(suffix_len) <= self.pages_per_seq:
                break
            n_pre -= 1
            shared = shared[:n_pre]
        if not n_pre:
            suffix_len = true_len
        region = _region_for(suffix_len)
        total_need = max(n_pre + region,
                         -(-(true_len + req.max_new_tokens) // bs_))
        # hold the shared pages BEFORE the availability check: shared pages
        # sitting in _evictable would otherwise be double-counted as
        # allocatable, letting _alloc_pages run dry
        self._ref_pages(shared)
        if total_need - n_pre > self._available_pages():
            self._unref_pages(shared)
            if not self._active and not self._prefilling:
                # nothing running and it still can't admit: with the pool
                # otherwise idle that means it NEVER fits (needs more pages
                # than exist). Fail it alone, keep the queue draining.
                self._fail_request(req, RuntimeError(
                    f"request {req.rid} needs more pages than the pool holds "
                    f"({true_len}+{req.max_new_tokens} tokens vs "
                    f"{(self.num_pages - 1) * self.page_size} pool tokens)"))
                if adm is not None:
                    adm.end("error", error=req.error_message)
                return "failed"
            self.stats["deferred_admissions"] += 1
            if adm is not None:  # honest trace: each deferred probe shows
                adm.end("deferred", need_pages=total_need - n_pre)
            return "deferred"
        if self.enable_prefix_cache:
            # hit-rate denominator, counted once per ADMISSION (a deferred
            # request re-enters try_admit_one every decode block and must
            # not inflate it): every full prompt page that could have come
            # from cache
            _M_PREFIX_LOOKUP.inc((true_len - 1) // bs_)
        slot = self.free_slots.pop()
        new_pages = self._alloc_pages(total_need - n_pre)
        self._ref_pages(new_pages)
        pages = shared + new_pages
        self.stats["peak_pages"] = max(self.stats["peak_pages"],
                                       self._pages_in_use)
        req.pages = pages
        req.slot = slot
        req.t_admit = time.monotonic()
        sampling = req.sampling
        if self._ragged:
            # ---- ragged admission (ISSUE 20): reserve pages and install
            # the page-table row NOW; the prompt streams into the pool via
            # step()'s MIXED ragged dispatches (prefill chunks co-scheduled
            # with everyone's decode rows in one program) — admission does
            # no device work at all, for any prompt length, adapter, or
            # kv dtype. Prefix-cache hits seed `consumed` past the shared
            # pages, exactly like the legacy chunk ladder's filled_pages.
            req.tokens = list(prompt)  # tok0 appended at graduation
            if n_pre:
                self.stats["prefix_hit_pages"] += n_pre
                _M_PREFIX_HIT.inc(n_pre)
            if sampling[0] and req.key_base is None:
                req.key_base = np.asarray(
                    jax.random.fold_in(jax.random.PRNGKey(req.seed),
                                       req.rid))
            row = np.zeros(self.pages_per_seq, np.int32)
            row[:len(pages)] = pages
            self.page_table[slot] = row
            self.lengths[slot] = n_pre * bs_
            st = _PrefillState(req, pages, n_pre, digests)
            st.consumed = n_pre * bs_
            self._prefilling[slot] = st
            self._active_sampling = sampling
            if ad is not None:
                self._slot_adapter[slot] = ad
                self._active_lora_rank = ad.rank
            if adm is not None:
                adm.end("ok", slot=slot, pages=len(pages),
                        prefix_hit_pages=n_pre, ragged=True)
            return "admitted"
        if self.prefill_chunk and suffix_len > self.prefill_chunk \
                and ad is None:
            # reserve-then-stream admission: the prompt lands chunk by
            # chunk in step(), interleaved with everyone else's decode
            # blocks, instead of one monolithic bucketed dispatch.
            # Adapter requests take the monolithic path below instead —
            # a scoped degradation (one big dispatch, never wrong tokens)
            # that keeps the chunk ladder free of lora program variants
            req.tokens = list(prompt)  # tok0 appended at graduation
            if n_pre:
                self.stats["prefix_hit_pages"] += n_pre
                _M_PREFIX_HIT.inc(n_pre)
            self._prefilling[slot] = _PrefillState(req, pages, n_pre,
                                                  digests)
            self._active_sampling = sampling
            if adm is not None:
                adm.end("ok", slot=slot, pages=len(pages),
                        prefix_hit_pages=n_pre, chunked=True)
            # the FIRST chunk dispatches here — admission stays one
            # bounded unit of device work, like a short prompt's prefill
            return self._prefill_chunk_step(slot)
        sbucket = prompt_bucket(suffix_len)
        ids_p = np.zeros((1, sbucket), np.int32)
        ids_p[0, :suffix_len] = prompt[n_pre * bs_:]
        if ad is None:
            progs = ([("gather", n_pre),
                      ("suffix", n_pre, sbucket, sampling)]
                     if n_pre else [("prefill", sbucket, sampling)])
        else:
            progs = ([("gather", n_pre),
                      ("lora_suffix", n_pre, sbucket, sampling, ad.rank)]
                     if n_pre
                     else [("lora_prefill", sbucket, sampling, ad.rank)])
            # per-request adapter operands (digest-keyed device cache);
            # eager transfers, hoisted outside the locked dispatch
            a_dev, b_dev = self._lora_dev(ad)
            scale_dev = jnp.float32(ad.scale)
        if sampling[0] and req.key_base is None:
            # key_base = fold_in(PRNGKey(seed), rid): the request's own
            # stream root, so its sampled tokens are independent of which
            # co-tenants (or which replica) it landed with. Materialized
            # BEFORE the locked dispatch (blocking-under-lock): it depends
            # only on (seed, rid) — pure jax, no framework Tensor state —
            # and its 8-byte device->host pull must not extend the hold
            # every sibling dispatcher queues behind
            req.key_base = np.asarray(
                jax.random.fold_in(jax.random.PRNGKey(req.seed), req.rid))
        t_p0 = time.monotonic()
        try:
            with self._locked_dispatch(*progs, ("insert", sbucket)), \
                    _trace.span("serve.prefill"), self._xprof_annotation(req):
                k0 = (jax.random.fold_in(jnp.asarray(req.key_base), 0)
                      if sampling[0]
                      else jnp.zeros((2,), jnp.uint32))  # greedy ignores it
                chaos.site("serve.prefill")
                if n_pre:
                    self.stats["prefix_hit_pages"] += n_pre
                    _M_PREFIX_HIT.inc(n_pre)
                    ks_pre, vs_pre = self._gather_prefix(n_pre)(
                        tuple(self.pools), jnp.asarray(shared, jnp.int32))
                    if ad is None:
                        tok0, ks, vs = self._prefill_suffix(
                            n_pre, sbucket, sampling)(
                            state, ks_pre, vs_pre, jnp.asarray(ids_p),
                            jnp.int32(suffix_len), k0)
                    else:
                        tok0, ks, vs = self._lora_prefill_suffix(
                            n_pre, sbucket, sampling, ad.rank)(
                            state, ks_pre, vs_pre, jnp.asarray(ids_p),
                            jnp.int32(suffix_len), k0, a_dev, b_dev,
                            scale_dev)
                elif ad is None:
                    tok0, ks, vs = self._prefill(sbucket, sampling)(
                        state, jnp.asarray(ids_p), jnp.int32(suffix_len), k0)
                else:
                    tok0, ks, vs = self._lora_prefill(
                        sbucket, sampling, ad.rank)(
                        state, jnp.asarray(ids_p), jnp.int32(suffix_len),
                        k0, a_dev, b_dev, scale_dev)
                page_ids = jnp.asarray(new_pages[:region], jnp.int32)
                self.pools = list(self._insert(sbucket)(
                    tuple(self.pools), ks, vs, page_ids))
                # sync INSIDE the guard: device-side prefill errors surface
                # at this host transfer, not at dispatch — outside the try
                # they would leak the popped slot + reffed pages and
                # (online) kill the whole replica instead of failing this
                # request alone. This is the prefill's designated readback
                # (the first token gates admission bookkeeping).
                tok0 = int(tok0)
        except Exception as e:  # error isolation: fail THIS request alone
            self._unref_pages(pages)
            self.free_slots.append(slot)
            self._fail_request(req, e)
            if adm is not None:
                adm.end("error", error=req.error_message)
            return "failed"
        dt = time.monotonic() - t_p0
        if _trace.enabled():
            # serving goodput split: a cold section is compile stall, not
            # prefill throughput (ISSUE 7 satellite)
            _goodput.serving_note(
                "compile" if self._last_dispatch_cold else "prefill", dt)
        if adm is not None:
            adm.span_at("prefill", dt, dt, bucket=sbucket,
                        prefix_hit_pages=n_pre,
                        cold=self._last_dispatch_cold)
        if self.enable_prefix_cache:
            self._index_prompt_pages(true_len, pages, n_pre, digests)
        req.tokens = list(prompt)
        status = self._activate(slot, req, tok0)
        if adm is not None:
            adm.end("ok", slot=slot, pages=len(pages),
                    prefix_hit_pages=n_pre)
        return status

    def _activate(self, slot, req, tok0):
        """Shared admission epilogue (monolithic prefill AND chunked
        graduation — one copy, so the activation protocol cannot drift
        between the two paths): install the page-table row, stamp the
        first token, register the request in the decode group, fire the
        callback, and retire immediately on a first-token eos / exhausted
        budget. Returns "done" or "admitted"."""
        row = np.zeros(self.pages_per_seq, np.int32)
        row[:len(req.pages)] = req.pages
        self.page_table[slot] = row
        self.lengths[slot] = len(req.prompt)
        now = time.monotonic()
        req.t_first_token = now
        _M_TTFT.observe(now - req.t_enqueue)
        if req.trace is not None:
            req.trace.event("first_token",
                            ttft_s=round(now - req.t_enqueue, 6))
        _M_TOKENS.inc()
        req.tokens.append(tok0)
        req.n_generated = 1
        req.n_dispatched = 1
        req.last_token = tok0
        # register BEFORE the user callback: if it raises, the cleanup path
        # must see this slot to free its pages
        self._active[slot] = req
        self._active_sampling = req.sampling
        if req.adapter is not None:
            # the group becomes (or stays) a lora group of this rank:
            # decode dispatches switch to the lora programs, plain
            # co-tenants ride the zero slot bit-identically
            self._slot_adapter[slot] = req.adapter
            self._active_lora_rank = req.adapter.rank
        if req.on_token is not None:
            req.on_token(req.rid, tok0)
        if (req.eos_token_id is not None and tok0 == req.eos_token_id) \
                or req.n_generated >= req.max_new_tokens:
            self._retire(slot)
            return "done"
        return "admitted"

    def _chunk_plan(self, suffix_len):
        """(full_chunks, final_len, region_pages) for a chunked suffix.
        Non-final chunks are exactly ``prefill_chunk`` tokens (a whole
        number of pages, so the next chunk's prefix gather reads no pad);
        the final chunk keeps >=1 token so its logits produce the first
        sampled token, and pads to its own prompt bucket like the
        monolithic path."""
        c = self.prefill_chunk
        n_full = (suffix_len - 1) // c
        final_len = suffix_len - n_full * c
        region = (n_full * (c // self.page_size)
                  + self._pages_for_bucket(prompt_bucket(final_len),
                                           self.page_size))
        return n_full, final_len, region

    def _prefill_chunk_step(self, slot):
        """Dispatch ONE prefill chunk for ``slot``. Chunk j is the prefix-
        cache machinery applied to the engine's own partial work: gather
        the pages already inserted, prefill the next chunk against them,
        scatter its KV into the next pages. On the final chunk the request
        graduates — samples tok0 with the same per-request key the
        monolithic path uses (bit-identical first token), installs its
        page-table row, and joins the decode group. Returns "admitted"
        (still prefilling, or now decoding), "done" (graduated AND retired
        on its first token), or "failed" (isolated failure; resources
        freed, co-tenants unaffected)."""
        st = self._prefilling[slot]
        req = st.req
        bs = self.page_size
        prompt = req.prompt
        true_len = len(prompt)
        filled = st.filled_pages
        done_tokens = filled * bs
        rest = true_len - done_tokens
        final = rest <= self.prefill_chunk
        clen = rest if final else self.prefill_chunk
        cbucket = prompt_bucket(clen) if final else clen
        npg = self._pages_for_bucket(cbucket, bs)
        sampling = req.sampling
        state = self._captured_state()
        ids = np.zeros((1, cbucket), np.int32)
        ids[0, :clen] = prompt[done_tokens:done_tokens + clen]
        progs = ([("gather", filled), ("suffix", filled, cbucket, sampling)]
                 if filled else [("prefill", cbucket, sampling)])
        if final and sampling[0] and req.key_base is None:
            # same hoist as the unchunked admission path: (seed, rid)-only
            # work plus an 8-byte pull stays outside the locked dispatch
            req.key_base = np.asarray(
                jax.random.fold_in(jax.random.PRNGKey(req.seed), req.rid))
        t_p0 = time.monotonic()
        try:
            with self._locked_dispatch(*progs, ("insert", cbucket)), \
                    _trace.span("serve.prefill"), self._xprof_annotation(req):
                k0 = (jax.random.fold_in(jnp.asarray(req.key_base), 0)
                      if final and sampling[0]
                      else jnp.zeros((2,), jnp.uint32))
                chaos.site("serve.prefill")
                if filled:
                    ks_pre, vs_pre = self._gather_prefix(filled)(
                        tuple(self.pools),
                        jnp.asarray(st.pages[:filled], jnp.int32))
                    tok0, ks, vs = self._prefill_suffix(
                        filled, cbucket, sampling)(
                        state, ks_pre, vs_pre, jnp.asarray(ids),
                        jnp.int32(clen), k0)
                else:
                    tok0, ks, vs = self._prefill(cbucket, sampling)(
                        state, jnp.asarray(ids), jnp.int32(clen), k0)
                page_ids = jnp.asarray(st.pages[filled:filled + npg],
                                       jnp.int32)
                self.pools = list(self._insert(cbucket)(
                    tuple(self.pools), ks, vs, page_ids))
                # readback INSIDE the try for EVERY chunk (the monolithic
                # path's designated sync point, same rationale): a device-
                # side chunk failure must surface here, where this
                # request's resources free and it fails ALONE — deferred,
                # it would materialize at a later unrelated decode
                # readback, outside any per-request guard, and take the
                # whole replica down. The wait itself costs little: this
                # chunk chains behind the in-flight decode block whose
                # readback happens later in the same step() anyway.
                tok0 = int(tok0)
        except Exception as e:
            del self._prefilling[slot]
            self._unref_pages(st.pages)
            self.free_slots.append(slot)
            if not self._active and not self._prefilling:
                self._active_sampling = None
                self._active_lora_rank = None
            self._fail_request(req, e)
            if req.trace is not None:
                req.trace.event("prefill_chunk_failed",
                                error=req.error_message)
            return "failed"
        _M_CHUNKS.inc()
        dt = time.monotonic() - t_p0
        if _trace.enabled():
            _goodput.serving_note(
                "compile" if self._last_dispatch_cold else "prefill", dt)
        if req.trace is not None:
            req.trace.span_at("prefill_chunk", dt, dt,
                              filled_pages=filled, tokens=clen, final=final,
                              cold=self._last_dispatch_cold)
        if not final:
            st.filled_pages = filled + npg
            return "admitted"
        # ---- graduation: join the decode group -----------------------------
        del self._prefilling[slot]
        if self.enable_prefix_cache:
            self._index_prompt_pages(true_len, st.pages, st.n_pre0,
                                     st.digests)
        return self._activate(slot, req, tok0)

    def _advance_prefill(self):
        """Land ONE pending prefill chunk per mid-prefill slot (called
        between decode blocks, so a long prompt pays out its prefill
        without ever monopolizing the device — each slot advances one
        small chunk per decode block). Advancing every slot instead of
        round-robining ONE keeps co-admitted long prompts graduating
        nearly together: a decode block costs the same at any occupancy,
        so staggered graduations that decode 1-2 rows at a time nearly
        double the block count for the same tokens (measured 133 vs 76
        steps on a 4-long + 12-short workload). Returns the requests that
        reached a terminal state (graduated straight to done, or failed
        in isolation)."""
        out = []
        for slot in list(self._prefilling):
            req = self._prefilling[slot].req
            status = self._prefill_chunk_step(slot)
            if status in ("done", "failed"):
                out.append(req)
        return out

    def _admit_from(self, queue):
        """Admit from the head of ``queue`` (a deque of EngineRequests)
        until one defers — FIFO, the batch path's no-skip-ahead contract
        (the frontend's scheduler reorders BEFORE requests reach this
        point). Pops every request that reached a terminal state."""
        admitted = False
        while queue and self.free_slots:
            status = self.try_admit_one(queue[0])
            if status == "deferred":
                break
            queue.popleft()
            admitted = True
        self._update_gauges()
        return admitted

    def step(self):
        """One scheduling round: sweep cancellations, land at most one
        prefill chunk, advance the decode pipeline, sweep timeouts.
        Returns the EngineRequests that reached a terminal state during
        this step; ``[]`` when idle.

        Decode pipeline: under ``async_decode`` the engine keeps ONE block
        in flight — block k+1 is dispatched chained off block k's device-
        resident last-token row BEFORE block k's tokens are read back, so
        the host emit/retire/admit work (and the caller's scheduling
        between step() calls) runs under block k+1's device execution.
        Retirement and admission stay at readback points; a slot whose
        request finished mid-block simply has its overshoot tokens
        discarded (its KV writes stay inside its still-held page
        reservation, and any page later reallocated is fully rewritten by
        the new tenant's prefill/decode before it is ever read). The sync
        path (``async_decode=False``) dispatches and reads back in one
        call — the pre-pipeline behavior, kept as the bench baseline."""
        if self._ragged:
            return self._step_ragged()
        # requests that retired under an out-of-band _settle_inflight
        # readback surface here, so the frontend's step-driven finish path
        # sees every terminal request exactly once
        retired = self._pending_retired
        self._pending_retired = []
        # cancellation sweep first: no decode/prefill compute for a dead
        # request
        for slot in list(self._active):
            if self._active[slot].cancelled:
                retired.append(self._retire(slot))
        for slot in list(self._prefilling):
            if self._prefilling[slot].req.cancelled:
                retired.append(self._abort_prefill(slot))
        # one prefill chunk between decode blocks: long prompts pay out
        # without stalling in-flight requests' TPOT
        retired.extend(self._advance_prefill())
        if self.async_decode:
            prev = self._inflight
            if prev is not None:
                # overlap: enqueue block k+1 BEFORE block k's readback —
                # the emit/retire work below runs under its execution
                self._inflight = self._dispatch_decode(chain=prev)
                retired.extend(self._process_block(prev))
            if self._inflight is None and self._active:
                self._inflight = self._dispatch_decode()
        elif self._active:
            rec = self._dispatch_decode()
            if rec is not None:
                retired.extend(self._process_block(rec))
        now = time.monotonic()
        for slot in list(self._active):
            r = self._active[slot]
            if r.timeout_s is not None and now - r.t_admit > r.timeout_s:
                # deadline hit: return what it got, free the slot
                self.stats["timed_out_requests"] += 1
                counters.bump("fault.serve.request_timeout")
                r.timed_out = True
                retired.append(self._retire(slot))
        for slot in list(self._prefilling):
            r = self._prefilling[slot].req
            if r.timeout_s is not None and now - r.t_admit > r.timeout_s:
                self.stats["timed_out_requests"] += 1
                counters.bump("fault.serve.request_timeout")
                retired.append(self._abort_prefill(slot, timed_out=True))
        self._update_gauges()
        return retired

    def _step_ragged(self):
        """step() twin for ragged mode (ISSUE 20): NO separate prefill
        advancement — pending prompt chunks ride inside the decode
        dispatch itself (_dispatch_ragged), so a step is one mixed
        dispatch + one readback whatever the admission mix. Cancellation
        and timeout sweeps are the legacy step()'s, verbatim."""
        retired = self._pending_retired
        self._pending_retired = []
        for slot in list(self._active):
            if self._active[slot].cancelled:
                retired.append(self._retire(slot))
        for slot in list(self._prefilling):
            if self._prefilling[slot].req.cancelled:
                retired.append(self._abort_prefill(slot))
        if self.async_decode:
            prev = self._inflight
            if prev is not None:
                self._inflight = self._dispatch_ragged(chain=prev)
                retired.extend(self._process_block(prev))
            if self._inflight is None and (self._active or self._prefilling):
                self._inflight = self._dispatch_ragged()
        elif self._active or self._prefilling:
            rec = self._dispatch_ragged()
            if rec is not None:
                retired.extend(self._process_block(rec))
        now = time.monotonic()
        for slot in list(self._active):
            r = self._active[slot]
            if r.timeout_s is not None and now - r.t_admit > r.timeout_s:
                self.stats["timed_out_requests"] += 1
                counters.bump("fault.serve.request_timeout")
                r.timed_out = True
                retired.append(self._retire(slot))
        for slot in list(self._prefilling):
            r = self._prefilling[slot].req
            if r.timeout_s is not None and now - r.t_admit > r.timeout_s:
                self.stats["timed_out_requests"] += 1
                counters.bump("fault.serve.request_timeout")
                retired.append(self._abort_prefill(slot, timed_out=True))
        self._update_gauges()
        return retired

    def _dispatch_ragged(self, chain=None):
        """Dispatch one ragged step: when prompt chunks are pending, the
        MIXED program carries them alongside every decode row; with no
        prefill in flight the fixed-k decode block (via _dispatch_decode,
        which pins k = decode_block in ragged mode) runs alone."""
        if self._prefilling:
            return self._dispatch_ragged_mixed(chain)
        return self._dispatch_decode(chain=chain)

    def _dispatch_ragged_mixed(self, chain):
        """One mixed ragged dispatch: every decode row (one feed token
        each) plus up to ``_ragged_chunk`` prompt tokens of mid-prefill
        slots, packed into a single [T]-token program that then scans the
        remaining k-1 decode steps. Prompts landing their LAST chunk
        graduate here — the packed pass samples their first token and the
        scan decodes them alongside everyone else, so TTFT never waits
        for a separate prefill dispatch. Shortest-remaining-first chunk
        scheduling drains near-done prompts into the decode group ASAP."""
        sampling = self._active_sampling
        lora_rank = self._active_lora_rank
        state = self._captured_state()
        k = self.decode_block
        S = self.max_seqs
        T = self._ragged_tokens
        budget = self._ragged_chunk
        sched = []
        order = sorted(self._prefilling.items(),
                       key=lambda kv: (len(kv[1].req.prompt)
                                       - kv[1].consumed, kv[0]))
        for slot, st in order:
            if budget <= 0:
                break
            rem = len(st.req.prompt) - st.consumed
            take = min(rem, budget)
            budget -= take
            sched.append((slot, st, take, take == rem))
        covered = ({s for s, r in chain.rows
                    if self._active.get(s) is r} if chain is not None
                   else set())
        chunk_rows = {slot: (st, take, final)
                      for slot, st, take, final in sched}
        tok_block = np.zeros(T, np.int32)
        row_of = np.zeros(T, np.int32)
        token_pos = np.zeros(T, np.int32)
        valid = np.zeros(T, bool)
        use_last = np.zeros((S, 1), bool)
        q_lens = np.zeros(S, np.int32)
        lengths_op = np.zeros(S, np.int32)
        caps = np.zeros(S, np.int32)   # 0 = frozen/scratch-routed in scan
        bases = np.zeros((S, 2), np.uint32)
        idxs = np.zeros(S, np.int32)
        part = []    # decode participants: active rows + graduating rows
        grads = []   # (slot, st) graduating at THIS dispatch
        pos = 0
        for slot in range(S):
            r = self._active.get(slot)
            if r is not None:
                caps[slot] = len(r.prompt) + r.max_new_tokens - 1
                # host twin of the in-program freeze clamp: an over-budget
                # row's feed position must not index past its reservation
                base = min(int(self.lengths[slot]), int(caps[slot]))
                q_lens[slot] = 1
                lengths_op[slot] = base
                row_of[pos] = slot
                token_pos[pos] = base
                valid[pos] = True
                if slot in covered:
                    use_last[slot, 0] = True
                else:
                    tok_block[pos] = r.last_token
                if sampling[0]:
                    bases[slot] = r.key_base
                    idxs[slot] = r.n_dispatched
                part.append((slot, r))
                pos += 1
            elif slot in chunk_rows:
                st, take, final = chunk_rows[slot]
                req = st.req
                sl = slice(pos, pos + take)
                tok_block[sl] = req.prompt[st.consumed:st.consumed + take]
                row_of[sl] = slot
                token_pos[sl] = int(self.lengths[slot]) + np.arange(take)
                valid[sl] = True
                q_lens[slot] = take
                lengths_op[slot] = self.lengths[slot]
                pos += take
                if final:
                    caps[slot] = len(req.prompt) + req.max_new_tokens - 1
                    if sampling[0]:
                        bases[slot] = req.key_base  # idx 0: first token
                    part.append((slot, req))
                    grads.append((slot, st))
        cu = np.zeros(S + 1, np.int32)
        cu[1:] = np.cumsum(q_lens)
        # non-participant rows (empty slots + still-mid-prefill prompts)
        # route their scan-step writes to the scratch page
        scan_pt = np.where((caps > 0)[:, None], self.page_table, 0)
        if chain is not None and use_last.any():
            last_dev = chain.last
        else:
            last_dev = jnp.zeros((S, 1), jnp.int32)
        if lora_rank is not None:
            ads = sorted({a.digest: a for a
                          in self._slot_adapter.values()}.values(),
                         key=lambda a: a.digest)
            a_stack, b_stack, l_scales, lpos = self._lora_stack(lora_rank,
                                                                ads)
            l_idx = np.zeros(S, np.int32)
            for slot, r in part:
                if r.adapter is not None:
                    l_idx[slot] = lpos[r.adapter.digest]
            l_idx = jnp.asarray(l_idx)

        def dispatch():
            chaos.site("serve.decode")
            args = (state, jnp.asarray(tok_block), jnp.asarray(cu),
                    jnp.asarray(row_of), jnp.asarray(token_pos),
                    jnp.asarray(valid), jnp.asarray(use_last), last_dev,
                    tuple(self.pools), jnp.asarray(self.page_table),
                    jnp.asarray(scan_pt), jnp.asarray(lengths_op),
                    jnp.asarray(caps), keys)
            if lora_rank is not None:
                return self._lora_ragged_fn(sampling, lora_rank)(
                    *args, a_stack, b_stack, l_scales, l_idx)
            return self._ragged_fn(sampling)(*args)

        progs = [("ragged", sampling) if lora_rank is None
                 else ("lora_ragged", sampling, lora_rank)]
        if sampling[0]:
            progs.append(("keys", k))
        host = None
        t0 = time.monotonic()
        with self._locked_dispatch(*progs), _trace.span("serve.decode"):
            if sampling[0]:
                idx_mat = idxs[None, :] + np.arange(k, dtype=np.int32)[:, None]
                keys = _KEYS_FROM_BASE(jnp.asarray(bases),
                                       jnp.asarray(idx_mat))
            else:
                keys = jnp.zeros((k, S, 2), jnp.uint32)
            blk, pools = self.retry_policy.run(dispatch, name="serve.decode")
            if not self.async_decode:
                host = np.asarray(blk)  # serve-readback-ok
        self.pools = list(pools)  # lint: shared-mutation-without-lock-ok (engine fields are dispatcher-owned — single-threaded by contract)
        cold = self._last_dispatch_cold
        if _trace.enabled() and cold:
            _goodput.serving_note("compile", time.monotonic() - t0)
        n_chunk = sum(t for _, _, t, _ in sched)
        _dp = _devprof._PLANE
        if _dp is not None and not cold:
            prog_key = (f"serve.ragged[k{k},s{sampling}]"
                        if lora_rank is None else
                        f"serve.lora_ragged[r{lora_rank},k{k},s{sampling}]")
            _dp.tick(prog_key, t0, blk, tokens=k * len(part) + n_chunk,
                     context="serve.decode")
        last = blk[k - 1][:, None]
        if hasattr(blk, "copy_to_host_async"):
            blk.copy_to_host_async()
        # ---- bookkeeping: chunks consumed, graduations, dispatch counts
        for slot, st, take, final in sched:
            _M_CHUNKS.inc()
            st.consumed += take
            self.lengths[slot] += take
        for slot, st in grads:
            # graduation at DISPATCH: the packed pass sampled tok0 and the
            # scan is already decoding this row — it joins the group now;
            # all k of its tokens arrive at this block's readback
            del self._prefilling[slot]
            if self.enable_prefix_cache:
                self._index_prompt_pages(len(st.req.prompt), st.pages,
                                         st.n_pre0, st.digests)
            st.req.n_dispatched = 0
            self._active[slot] = st.req
        for slot, r in part:
            r.n_dispatched += k
            self.lengths[slot] += k
        for slot, st in grads:
            # decode invariant lengths = len(prompt) + n_dispatched - 1:
            # the packed pass wrote the prompt's KV (lengths += take above)
            # and each scan write lands one BEHIND its dispatch count (the
            # boundary token fed at position true_len, not true_len+1)
            self.lengths[slot] -= 1
        return _InflightBlock(blk, last, k, part, t0, host=host, cold=cold)

    def _dispatch_decode(self, chain=None):
        """Dispatch ONE decode block over the current active set WITHOUT
        reading it back. ``chain`` is the still-in-flight previous block:
        its device-resident last-token row feeds this block for every
        slot it covered (the autoregressive dependency never round-trips
        to the host); freshly admitted slots merge their host-known first
        token in with one tiny fused select. Returns the new
        _InflightBlock, or None when nothing can dispatch — empty active
        set, or some row's token budget is fully dispatched (the caller
        must read the in-flight block back first so those rows retire)."""
        if not self._active:
            return None
        budgets = [r.max_new_tokens - r.n_dispatched
                   for r in self._active.values()]
        # Async pipeline: block size from the LARGEST remaining budget
        # (power of two so the compile cache stays at log2(decode_block)
        # programs) — short-budget rows ride along under their in-program
        # length caps instead of dragging k down to the batch minimum,
        # which under staggered admissions fragments every block to k=1-2
        # and doubles dispatches. Sync mode keeps the pre-pipeline
        # min-remaining policy verbatim (it IS the pre-PR engine — the
        # bench baseline; the caps are the identity there since k never
        # exceeds any row's budget).
        remaining = max(budgets) if self.async_decode else min(budgets)
        if remaining <= 0:
            return None  # every row fully dispatched: read back, retire
        sampling = self._active_sampling
        lora_rank = self._active_lora_rank
        state = self._captured_state()
        if self._ragged:
            # ragged mode (ISSUE 20): ONE fixed block size — the
            # power-of-two k ladder is gone; short-budget rows ride under
            # their in-program caps and overshoot is discarded at emit
            k = self.decode_block
        else:
            k = min(self.decode_block, remaining)
            k = 1 << (k.bit_length() - 1)
        rows = list(self._active.items())
        # a chained slot must still belong to the SAME request — a slot
        # retired and re-admitted while the block was in flight feeds its
        # new tenant's host-known token, not the dead tenant's device row
        covered = ({s for s, r in chain.rows
                    if self._active.get(s) is r} if chain is not None
                   else ())
        toks = np.zeros((self.max_seqs, 1), np.int32)
        fresh = np.zeros((self.max_seqs, 1), bool)
        bases = np.zeros((self.max_seqs, 2), np.uint32)
        idxs = np.zeros(self.max_seqs, np.int32)
        caps = np.zeros(self.max_seqs, np.int32)  # empty slots freeze at 0
        for slot, r in rows:
            # last page-reserved position: an over-budget row's writes
            # freeze here inside the program (see _decode_block_fn)
            caps[slot] = len(r.prompt) + r.max_new_tokens - 1
            if slot not in covered:
                toks[slot, 0] = r.last_token
                fresh[slot, 0] = True
            if sampling[0]:
                bases[slot] = r.key_base
                idxs[slot] = r.n_dispatched
        if chain is None:
            feed = jnp.asarray(toks)
        elif fresh.any():
            feed = jnp.where(jnp.asarray(fresh), jnp.asarray(toks),
                             chain.last)
        else:
            feed = chain.last
        if lora_rank is not None:
            # lora group: fixed-depth stacked adapter operands + per-row
            # gather indices (0 = the zero slot for plain co-tenants).
            # Digest-sorted so the stack cache key — and row indexing —
            # is deterministic for a given working set.
            ads = sorted({a.digest: a for a
                          in self._slot_adapter.values()}.values(),
                         key=lambda a: a.digest)
            a_stack, b_stack, l_scales, pos = self._lora_stack(lora_rank,
                                                               ads)
            l_idx = np.zeros(self.max_seqs, np.int32)
            for slot, r in rows:
                if r.adapter is not None:
                    l_idx[slot] = pos[r.adapter.digest]
            l_idx = jnp.asarray(l_idx)
        # the chaos site fires BEFORE the jitted call, so an injected
        # outage retries against intact pools; a real failure after the
        # dispatch donated them is not retriable (the retry would read
        # donated buffers) and raises out through the caller's cleanup
        def dispatch():
            chaos.site("serve.decode")
            if lora_rank is not None:
                if k == 1:
                    nxt, pools = self._lora_decode(sampling, lora_rank)(
                        state, feed, tuple(self.pools),
                        jnp.asarray(self.page_table),
                        jnp.asarray(self.lengths), jnp.asarray(caps),
                        keys[0], a_stack, b_stack, l_scales, l_idx)
                    return nxt[None], pools
                return self._lora_block_fn(sampling, lora_rank, k)(
                    state, feed, tuple(self.pools),
                    jnp.asarray(self.page_table), jnp.asarray(self.lengths),
                    jnp.asarray(caps), keys, a_stack, b_stack, l_scales,
                    l_idx)
            if k == 1:
                nxt, pools = self._decode(sampling)(
                    state, feed, tuple(self.pools),
                    jnp.asarray(self.page_table),
                    jnp.asarray(self.lengths), jnp.asarray(caps), keys[0])
                return nxt[None], pools
            return self._decode_block_fn(sampling, k)(
                state, feed, tuple(self.pools),
                jnp.asarray(self.page_table), jnp.asarray(self.lengths),
                jnp.asarray(caps), keys)

        if lora_rank is not None:
            progs = [("lora_decode", sampling, lora_rank) if k == 1
                     else ("lora_block", sampling, lora_rank, k)]
        else:
            progs = [("decode", sampling) if k == 1
                     else ("block", sampling, k)]
        if sampling[0]:
            progs.append(("keys", k))
        host = None
        t0 = time.monotonic()  # dispatch epoch: TPOT = readback - t0 per k
        with self._locked_dispatch(*progs), _trace.span("serve.decode"):
            if sampling[0]:
                idx_mat = idxs[None, :] + np.arange(k, dtype=np.int32)[:, None]
                keys = _KEYS_FROM_BASE(jnp.asarray(bases),
                                       jnp.asarray(idx_mat))
            else:
                # greedy ignores the keys entirely — skip the device work
                keys = jnp.zeros((k, self.max_seqs, 2), jnp.uint32)
            blk, pools = self.retry_policy.run(dispatch, name="serve.decode")
            if not self.async_decode:
                # legacy sync semantics: the readback happens INSIDE the
                # lock, exactly like the pre-pipeline engine — the lock
                # covers the whole device round trip, which is what made
                # replicas sharing a lock serialize their compute. The
                # async path's readback is lock-free in _process_block.
                host = np.asarray(blk)  # serve-readback-ok
        self.pools = list(pools)  # lint: shared-mutation-without-lock-ok (engine fields are dispatcher-owned — single-threaded by contract)
        cold = self._last_dispatch_cold
        if _trace.enabled() and cold:
            # a cold decode dispatch spent its wall tracing, not decoding —
            # the block's readback skips its 'decode' note (the cold flag
            # rides the _InflightBlock) so the same wall isn't counted twice
            _goodput.serving_note("compile", time.monotonic() - t0)
        _dp = _devprof._PLANE
        if _dp is not None and not cold:
            # device-time sampling (ISSUE 17): on cadence, ONE timed
            # dispatch — block on the token buffer inside devprof (the
            # devprof-seam) and bank device-seconds per emitted token
            # under the program's ledger key. Off cadence this is a
            # counter increment and the block stays fully async; cold
            # dispatches (compile wall) never enter the table.
            if lora_rank is not None:
                prog_key = (f"serve.lora_decode[r{lora_rank},s{sampling}]"
                            if k == 1 else
                            f"serve.lora_decode_block[r{lora_rank},k{k},"
                            f"s{sampling}]")
            else:
                prog_key = (f"serve.decode[s{sampling}]" if k == 1
                            else f"serve.decode_block[k{k},s{sampling}]")
            _dp.tick(prog_key, t0, blk, tokens=k * len(rows),
                     context="serve.decode")
        last = blk[k - 1][:, None]  # device row the NEXT block chains from
        if hasattr(blk, "copy_to_host_async"):
            blk.copy_to_host_async()  # transfer rides under the compute
        # dispatch-time accounting: for every SURVIVING slot this equals
        # what per-token emit accounting would produce (+k per block); a
        # slot that turns out to have finished mid-block is zeroed at
        # retire, so the overshoot never leaks
        for slot, r in rows:
            r.n_dispatched += k
            self.lengths[slot] += k
        return _InflightBlock(blk, last, k, rows, t0, host=host, cold=cold)

    def _process_block(self, rec):
        """The decode pipeline's designated readback point: block tokens
        come to the host, per-request emit/retire runs, TPOT lands."""
        if self.async_decode:
            # host time that ran while the device executed this block —
            # the latency the double-buffering hides per block
            _M_OVERLAP.observe(time.monotonic() - rec.t0)
        with _trace.span("serve.decode.sync"):
            try:
                block = (rec.host if rec.host is not None
                         else np.asarray(rec.blk))  # serve-readback-ok
            except Exception as e:
                # async-path OOM surfaces at readback, outside the
                # dispatch lock — same forensics seam as _locked_dispatch
                _compilemem.maybe_oom_report(e, program="serve.decode_block")
                raise
        # wall from dispatch to readback, normalized per token: the TPOT
        # the serving comparison papers report
        block_wall = time.monotonic() - rec.t0
        _M_TPOT.observe(block_wall / rec.k)
        if _trace.enabled() and not rec.cold:
            # serving goodput: dispatch→readback is the decode slice (under
            # async overlap it runs concurrently with host_emit/admit — the
            # split reports attribution, not a partition of wall clock). A
            # cold block already landed in 'compile' at dispatch.
            _goodput.serving_note("decode", block_wall)
        self.stats["decode_steps"] += rec.k
        retired = []
        t_e0 = time.monotonic()
        with _trace.span("serve.emit"):
            for slot, r in rec.rows:
                if r.finished or self._active.get(slot) is not r:
                    # retired while in flight (cancel/timeout/reroute):
                    # its overshoot tokens are discarded
                    continue
                if r.t_first_token is None:
                    # ragged graduation: the first token materializes at
                    # THIS readback (legacy paths stamp in _activate, where
                    # the prefill dispatch synced — never reached here)
                    now_ft = time.monotonic()
                    r.t_first_token = now_ft
                    _M_TTFT.observe(now_ft - r.t_enqueue)
                    if r.trace is not None:
                        r.trace.event("first_token",
                                      ttft_s=round(now_ft - r.t_enqueue, 6))
                if r.trace is not None:
                    # the request's view of this fused decode dispatch
                    r.trace.span_at("decode_block", block_wall, block_wall,
                                    k=rec.k)
                emitted = 0
                for s in range(rec.k):
                    tok = int(block[s, slot])
                    r.tokens.append(tok)
                    r.n_generated += 1
                    r.last_token = tok
                    emitted += 1
                    _M_TOKENS.inc()
                    if r.on_token is not None:
                        r.on_token(r.rid, tok)
                    if r.n_generated >= r.max_new_tokens or (
                            r.eos_token_id is not None
                            and tok == r.eos_token_id):
                        # mid-block EOS: rest of the block is discarded
                        retired.append(self._retire(slot))
                        break
                if r.trace is not None:
                    r.trace.event("emit", tokens=emitted,
                                  n_generated=r.n_generated)
        if _trace.enabled():
            _goodput.serving_note("host_emit", time.monotonic() - t_e0)
        return retired

    def drain(self):
        """Finish every admitted request WITHOUT admitting more; returns the
        retired EngineRequests. The frontend's replica-drain building block,
        and the escape hatch before calling batch serve() on an engine that
        still has online work in flight."""
        out = []
        while (self._active or self._prefilling
               or self._inflight is not None or self._pending_retired):
            out.extend(self.step())
        return out

    @staticmethod
    def _per_request(value, n, name):
        """Scalar | per-rid list | complete {rid: v} dict -> per-rid list
        (satellite: per-request max_new_tokens)."""
        if isinstance(value, dict):
            missing = [i for i in range(n) if i not in value]
            if missing:
                raise ValueError(
                    f"per-request {name} dict missing rids {missing}")
            return [int(value[i]) for i in range(n)]
        if isinstance(value, (list, tuple, np.ndarray)):
            if len(value) != n:
                raise ValueError(f"per-request {name} has {len(value)} "
                                 f"entries for {n} requests")
            return [int(v) for v in value]
        return [int(value)] * n

    def serve(self, prompts, max_new_tokens, eos_token_id=None,
              do_sample=False, temperature=1.0, top_k=0, top_p=1.0, seed=0,
              on_token=None, request_timeout_s=None, sampling_overrides=None,
              adapters=None):
        """Serve a list of int32 prompt arrays; returns a list of
        [len(prompt) + n_generated] arrays (stops at eos or max_new_tokens).
        Requests beyond the pool/slot capacity queue and join as earlier
        sequences retire — continuous batching.

        ``max_new_tokens`` is a scalar, a per-request list, or a complete
        {rid: n} dict. ``sampling_overrides`` (per-request list of dicts /
        None, or a partial {rid: dict}) overrides do_sample/temperature/
        top_k/top_p per request; requests sharing a sampling tuple
        co-schedule, others wait for the running group (the sampler is a
        compile-time constant of the decode program).

        Degradation contract (one request must never kill the batch):

        - a request whose PREFILL raises fails alone: its slot/pages free,
          its results entry is None, the exception lands in
          self.request_errors[rid] (and on the EngineRequest's
          error/error_message), and every co-tenant keeps serving;
        - a request that can NEVER fit the pool (needs more pages than
          exist) likewise fails alone instead of raising out of serve() —
          admission backpressure for merely-busy pools is unchanged
          (FIFO deferral, stats["deferred_admissions"]);
        - request_timeout_s bounds each request's wall-clock from admission:
          on expiry it retires with the tokens generated so far
          (stats["timed_out_requests"]) — the slot goes back to the queue's
          next request instead of a straggler pinning it forever.

        Sampling (do_sample/temperature/top_k/top_p — the dense generate()
        sampler math) draws each sequence from its OWN key stream
        fold_in(fold_in(seed, request_id), token_index), so a request's
        output is reproducible regardless of which co-tenants shared its
        batch.

        on_token(request_id, token_id) streams each generated token (incl.
        the prefill's first token) as soon as its decode step completes —
        the serving-callback hook for SSE-style responses.

        ``adapters`` (ISSUE 19) attaches per-request LoRA adapters: a
        single resolved ``serving.adapters.LoRAAdapter`` applied to every
        request, a per-request list (None entries = base model), or a
        sparse {rid: adapter} dict. Adapter requests co-schedule with
        same-rank adapter requests and with base requests riding the zero
        slot; a batch with NO adapters dispatches the untouched base
        programs byte-for-byte."""
        if self._active or self._prefilling or self._inflight is not None:
            raise RuntimeError(
                "serve() on an engine with active online requests — drain() "
                "the frontend-driven work first")
        default_sampling = canonical_sampling(do_sample, temperature,
                                              top_k, top_p)
        per_new = self._per_request(max_new_tokens, len(prompts),
                                    "max_new_tokens")
        # sampling_overrides dicts may be sparse ({rid: ov} for just the
        # requests that deviate), but a list must cover every request —
        # fail like _per_request does, not with a bare IndexError mid-build
        if (sampling_overrides is not None
                and not isinstance(sampling_overrides, dict)
                and len(sampling_overrides) != len(prompts)):
            raise ValueError(
                f"per-request sampling_overrides has "
                f"{len(sampling_overrides)} entries for "
                f"{len(prompts)} requests")
        # adapters: one-for-all object, per-request list, or sparse dict —
        # same shape rules as sampling_overrides (lists must cover every
        # request; dicts may be sparse)
        if (adapters is not None and isinstance(adapters, (list, tuple))
                and len(adapters) != len(prompts)):
            raise ValueError(
                f"per-request adapters has {len(adapters)} entries for "
                f"{len(prompts)} requests")
        # every serve() batch starts from a FRESH capture (old-code parity):
        # the version-keyed reuse below it only has to bridge admissions
        # and decode blocks within one batch / online stretch. Under the
        # compile lock: a sibling replica tracing the shared model must
        # not leak tracers into this walk (see _captured_state).
        ver = _core.tensor_mutation_version()
        with _COMPILE_LOCK:
            state = self.model.raw_state_dict()
        self._decode_state_cache = (ver, state)
        if self.enable_prefix_cache:
            self._refresh_cache_guard(state)
        reqs = []
        for rid, p in enumerate(prompts):
            samp = default_sampling
            if sampling_overrides is not None:
                ov = (sampling_overrides.get(rid)
                      if isinstance(sampling_overrides, dict)
                      else sampling_overrides[rid])
                if ov:
                    samp = canonical_sampling(
                        ov.get("do_sample", do_sample),
                        ov.get("temperature", temperature),
                        ov.get("top_k", top_k), ov.get("top_p", top_p))
            if adapters is None:
                ad = None
            elif isinstance(adapters, dict):
                ad = adapters.get(rid)
            elif isinstance(adapters, (list, tuple)):
                ad = adapters[rid]
            else:
                ad = adapters
            reqs.append(EngineRequest(
                rid, p, per_new[rid], eos_token_id=eos_token_id,
                sampling=samp, seed=seed, timeout_s=request_timeout_s,
                on_token=on_token, adapter=ad))
        # only after EVERY request constructed (construction validates and
        # can raise): escalating the error bound or counting requests first
        # would leak past the finally below, which only runs once the try
        # is entered
        self.request_errors = {}  # lint: shared-mutation-without-lock-ok (serve() owns the engine for the batch — single caller by contract)
        # every failed rid of THIS batch keeps its entry, however large
        self._request_errors_bound = max(1024, len(prompts))
        queue = deque(reqs)
        _M_QUEUE.set(len(queue))  # records the load peak via the gauge hwm
        try:
            with _trace.span("serve.admit"):
                self._admit_from(queue)
            _M_QUEUE.set(len(queue))
            while (queue or self._active or self._prefilling
                   or self._inflight is not None):
                if not (self._active or self._prefilling
                        or self._inflight is not None):
                    # an idle engine always resolves its queue head (admit
                    # or fail-alone) — reaching here means the admission
                    # invariant broke, and spinning would hang the caller
                    raise AssertionError(
                        "serve(): admission stalled with an idle engine")
                self.step()
                with _trace.span("serve.admit"):
                    self._admit_from(queue)
                _M_QUEUE.set(len(queue))
            return [r.result for r in reqs]
        finally:
            self._request_errors_bound = 1024
            # a raising on_token (or any mid-serve failure) must not leak a
            # warm engine's pages/slots: retire whatever is still active
            # (and drop any unprocessed in-flight block — its tokens are
            # lost with the requests they belonged to)
            self._inflight = None
            for slot in list(self._active):
                self._retire(slot)
            for slot in list(self._prefilling):
                self._abort_prefill(slot)
